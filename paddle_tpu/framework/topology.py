"""AOT pod-scale topology planning: compile for hardware you don't have.

The MLPerf TPU-pod playbook (Kumar et al., arXiv:1909.09756) makes the
case that pod-scale efficiency is decided by the layout — mesh shape,
per-device memory fit, collective placement — long before a job ever
runs. jax can *describe* a TPU topology with no hardware attached
(``jax.experimental.topologies.get_topology_desc``: version, ``NxMxK``
chip shape, ``num_slices``) and AOT-compile against the described
devices, so the whole plan — per-device HLO, cost analysis, predicted
per-device HBM, the comms summary — is computable on a CPU dev box.

This module is the generic layer under ``tools/topo_plan.py``:

- :func:`parse_topology` turns a spec string (``v4:2x2x1``,
  ``v5e:4x4``, ``cpu:8``) into a :class:`TopoSpec`;
- :func:`describe` resolves a spec to a device list — described TPU
  devices when the runtime supports it, the local (forced-count) CPU
  devices otherwise. The TPU describe call HANGS on hosts without a TPU
  runtime, so :func:`probe_tpu_topology` feasibility-checks it in a
  subprocess with a hard timeout (``PADDLE_TPU_TOPOLOGY_TIMEOUT``)
  first and callers degrade to the CPU mesh with an explicit reason;
- :func:`build_mesh` lays a ``data``/``fsdp``/``tp`` recipe over the
  devices (axis names map onto the repo's ``dp``/``fsdp``/``tp`` mesh
  conventions);
- :func:`aot_analyze` runs the ``trace -> lower -> compile`` pipeline
  on abstract inputs (``jax.ShapeDtypeStruct`` + shardings — nothing is
  materialized) and mines the executable the same way xla_insight mines
  the executor's cache misses: FLOPs, per-device memory, HLO text, and
  the shard_insight comms summary;
- :func:`memory_fit` / :func:`roofline` turn those numbers into the
  plan verdicts: does each device fit in its stated HBM, and what
  roughly bounds the step (compute / memory / collectives).

The per-chip constants are deliberately coarse public numbers — the
roofline is a planning estimate, not a benchmark.
"""
from __future__ import annotations

import re
import subprocess
import sys
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import flags as _flags

__all__ = [
    "TopoSpec", "TPU_CHIP_SPECS", "parse_topology", "probe_tpu_topology",
    "describe", "build_mesh", "abstract_value", "aot_analyze",
    "memory_fit", "roofline", "axis_bytes_breakdown",
    "axis_link_classes",
]

# approximate public per-chip numbers (bf16 peak FLOP/s, HBM bytes, HBM
# bandwidth, ICI bandwidth per link, a planning-grade cross-host DCN
# proxy per chip) — planning-grade, not benchmarks. dcn_gbps prices the
# slow link class for multi-slice layouts; commswatch's measured
# link-class table replaces both link terms once a round commits.
TPU_CHIP_SPECS: Dict[str, Dict[str, float]] = {
    "v4":  {"hbm_gb": 32.0, "peak_flops": 275e12, "hbm_gbps": 1228.0,
            "ici_gbps": 50.0, "dcn_gbps": 12.5},
    "v5e": {"hbm_gb": 16.0, "peak_flops": 197e12, "hbm_gbps": 819.0,
            "ici_gbps": 50.0, "dcn_gbps": 12.5},
    "v5p": {"hbm_gb": 95.0, "peak_flops": 459e12, "hbm_gbps": 2765.0,
            "ici_gbps": 100.0, "dcn_gbps": 25.0},
    "v6e": {"hbm_gb": 32.0, "peak_flops": 918e12, "hbm_gbps": 1640.0,
            "ici_gbps": 100.0, "dcn_gbps": 25.0},
    # the CPU fallback mesh: fictitious-but-stated numbers so the
    # roofline/fit math stays exercisable end to end on a dev box
    "cpu": {"hbm_gb": 16.0, "peak_flops": 197e12, "hbm_gbps": 819.0,
            "ici_gbps": 50.0, "dcn_gbps": 5.0},
}


@dataclass
class TopoSpec:
    """A parsed topology request."""

    platform: str                       # "tpu" | "cpu"
    version: str = "cpu"                # v4 / v5e / v5p / v6e / cpu
    shape: Tuple[int, ...] = ()         # chips per slice, e.g. (2, 2, 1)
    num_slices: int = 1
    raw: str = ""

    @property
    def devices_per_slice(self) -> int:
        n = 1
        for d in self.shape:
            n *= int(d)
        return n

    @property
    def n_devices(self) -> int:
        return self.devices_per_slice * max(1, self.num_slices)

    def chip_spec(self) -> Dict[str, float]:
        return TPU_CHIP_SPECS.get(self.version, TPU_CHIP_SPECS["cpu"])

    def topology_name(self) -> str:
        return f"{self.version}:{'x'.join(str(d) for d in self.shape)}"

    def to_dict(self) -> dict:
        return {
            "platform": self.platform, "version": self.version,
            "shape": list(self.shape), "num_slices": self.num_slices,
            "n_devices": self.n_devices, "raw": self.raw,
        }


_SPEC_RE = re.compile(
    r"^(?P<ver>v\d+[a-z]*|cpu)(?::(?P<shape>\d+(?:x\d+)*))?$")


def parse_topology(spec: str, num_slices: int = 1) -> TopoSpec:
    """``v4:2x2x1`` / ``v5e:4x4`` / ``cpu:8`` / ``cpu`` -> TopoSpec.
    TPU versions require an explicit NxMxK chip shape; ``cpu:N`` takes a
    flat device count (default: every local device)."""
    m = _SPEC_RE.match(spec.strip().lower())
    if not m:
        raise ValueError(
            f"unparseable topology {spec!r} (want e.g. 'v4:2x2x1', "
            f"'v5e:4x4', 'cpu:8')")
    ver = m.group("ver")
    shape = tuple(int(d) for d in (m.group("shape") or "").split("x") if d)
    if ver == "cpu":
        return TopoSpec(platform="cpu", version="cpu",
                        shape=shape or (0,), num_slices=1, raw=spec)
    if not shape:
        raise ValueError(
            f"TPU topology {spec!r} needs an explicit chip shape "
            f"(e.g. '{ver}:2x2x1')")
    return TopoSpec(platform="tpu", version=ver, shape=shape,
                    num_slices=max(1, int(num_slices)), raw=spec)


# ---------------------------------------------------------------------------
# describe (the get_topology_desc wrapper + the no-hardware degrade path)
# ---------------------------------------------------------------------------


_PROBE_CODE = """\
import jax
jax.config.update("jax_platforms", "cpu")
from jax.experimental.topologies import get_topology_desc
topo = get_topology_desc(platform="tpu", topology_name={name!r},
                         num_slices={num_slices})
print("TOPO_OK", len(topo.devices))
"""


def probe_tpu_topology(spec: TopoSpec,
                       timeout: Optional[float] = None
                       ) -> Tuple[bool, str]:
    """Can this host describe ``spec`` without hardware? The describe
    call initializes the TPU PJRT plugin, which HANGS (rather than
    failing) on machines without a TPU runtime — so the feasibility
    check runs in a throwaway subprocess under a hard timeout and the
    caller only ever calls :func:`describe` in-process after an OK.

    Returns (ok, reason); reason explains the SKIP when not ok."""
    if timeout is None:
        timeout = float(_flags.env_flag("PADDLE_TPU_TOPOLOGY_TIMEOUT"))
    code = _PROBE_CODE.format(name=spec.topology_name(),
                              num_slices=spec.num_slices)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=max(1.0, timeout))
    except subprocess.TimeoutExpired:
        return False, (
            f"get_topology_desc({spec.raw!r}) did not answer within "
            f"{timeout:.0f}s (no TPU runtime on this host)")
    if proc.returncode == 0 and "TOPO_OK" in (proc.stdout or ""):
        return True, "described"
    tail = ((proc.stderr or proc.stdout or "").strip().splitlines() or
            ["no output"])[-1]
    return False, f"get_topology_desc({spec.raw!r}) failed: {tail[:200]}"


def describe(spec: TopoSpec, probe_timeout: Optional[float] = None
             ) -> Tuple[Optional[List[Any]], str]:
    """Resolve a TopoSpec to a device list.

    TPU specs go through :func:`probe_tpu_topology` first; on success
    the in-process describe returns the *described* (hardware-free)
    devices. CPU specs use the local devices (``cpu:N`` requires N of
    them — start the process with
    ``--xla_force_host_platform_device_count=N``, the conftest/dryrun
    bootstrap). Returns (devices or None, source-or-reason)."""
    import jax

    if spec.platform == "tpu":
        ok, reason = probe_tpu_topology(spec, probe_timeout)
        if not ok:
            return None, reason
        from jax.experimental.topologies import get_topology_desc

        topo = get_topology_desc(platform="tpu",
                                 topology_name=spec.topology_name(),
                                 num_slices=spec.num_slices)
        return list(topo.devices), "described"
    devices = [d for d in jax.devices() if d.platform == "cpu"]
    want = spec.devices_per_slice or len(devices)
    if len(devices) < want:
        return None, (
            f"cpu topology wants {want} devices but only {len(devices)} "
            f"exist (re-exec with "
            f"--xla_force_host_platform_device_count={want})")
    return devices[:want], "cpu"


# ---------------------------------------------------------------------------
# mesh recipes over described devices
# ---------------------------------------------------------------------------


# topo_plan recipes speak the ROADMAP axis names; the repo's sharding
# rules (models/gpt.py, ShardingOptimizer) speak dp/fsdp/tp
AXIS_ALIASES = {"data": "dp", "dp": "dp", "fsdp": "fsdp", "tp": "tp",
                "sp": "sp", "pp": "pp"}


def build_mesh(devices: Sequence[Any], recipe):
    """Lay a recipe over ``devices`` as a named Mesh. ``recipe`` is
    either an explicit ``{data: D, fsdp: F, tp: T}`` dict (axes renamed
    to the repo's dp/fsdp/tp conventions, in recipe order; sizes must
    multiply to the device count) or a named preset from THE shared
    recipe table (``parallel/recipes.py`` — ``dp``/``fsdp``/``tp``/
    hybrids), so an AOT plan and the runtime executor resolve one
    definition and cannot drift."""
    from jax.sharding import Mesh

    if isinstance(recipe, str):
        from ..parallel.recipes import resolve_recipe

        return resolve_recipe(recipe, len(devices)).mesh(devices)

    axes: Dict[str, int] = {}
    for name, size in recipe.items():
        ax = AXIS_ALIASES.get(str(name).lower())
        if ax is None:
            raise ValueError(f"unknown mesh axis {name!r} "
                             f"(want one of {sorted(AXIS_ALIASES)})")
        if ax in axes:
            raise ValueError(f"duplicate mesh axis {name!r}")
        axes[ax] = int(size)
    n = 1
    for s in axes.values():
        n *= s
    if n != len(devices):
        raise ValueError(
            f"mesh recipe {recipe} needs {n} devices, topology has "
            f"{len(devices)}")
    dev_array = np.asarray(list(devices)).reshape(tuple(axes.values()))
    return Mesh(dev_array, tuple(axes.keys()))


def abstract_value(shape: Sequence[int], dtype, sharding=None):
    """ShapeDtypeStruct carrying a sharding: the abstract argument the
    AOT pipeline lowers against — nothing is ever materialized, which is
    what lets a laptop plan a 256-chip program."""
    import jax

    return jax.ShapeDtypeStruct(tuple(int(s) for s in shape), dtype,
                                sharding=sharding)


# ---------------------------------------------------------------------------
# the AOT analysis pipeline (trace -> lower -> compile -> mine)
# ---------------------------------------------------------------------------


def aot_analyze(fn, abstract_args: Sequence[Any], *, mesh=None,
                donate_argnums: Tuple[int, ...] = (),
                label: str = "plan") -> Dict[str, Any]:
    """AOT-compile ``fn`` at abstract (sharded) arguments and mine the
    executable: cost_analysis FLOPs/bytes (per partitioned device),
    memory_analysis byte classes, the post-optimization per-device HLO,
    and the shard_insight comms summary. The exact analysis xla_insight
    performs on executor cache misses, minus any real inputs."""
    import jax

    from . import shard_insight as _shard
    from . import xla_insight as _insight

    jitted = jax.jit(fn, donate_argnums=donate_argnums)
    if mesh is not None:
        with mesh:
            lowered = jitted.lower(*abstract_args)
            executable = lowered.compile()
    else:
        lowered = jitted.lower(*abstract_args)
        executable = lowered.compile()

    out: Dict[str, Any] = {"label": label, "flops": None,
                           "bytes_accessed": None, "cost_raw": {}}
    cost: Any = None
    try:
        cost = executable.cost_analysis()
    except Exception:
        pass
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if isinstance(cost, dict):
        out["cost_raw"] = {str(k): float(v) for k, v in cost.items()
                           if isinstance(v, (int, float))}
        out["flops"] = out["cost_raw"].get("flops")
        out["bytes_accessed"] = out["cost_raw"].get("bytes accessed")

    mem = _insight.memory_analysis_bytes(executable)
    out["memory"] = mem
    out["peak_bytes"] = mem.get("peak_bytes")
    # donation aliases outputs onto arguments: the donation-adjusted
    # resident estimate is what a fit verdict should use (the raw
    # args+outs+temps peak stays reported as the upper bound)
    alias = mem.get("alias_bytes") or 0
    if out["peak_bytes"]:
        out["fit_bytes"] = max(0, int(out["peak_bytes"]) - int(alias))
    else:
        out["fit_bytes"] = None

    hlo_text = None
    try:
        hlo_text = executable.as_text()
    except Exception:
        try:
            hlo_text = lowered.as_text()
        except Exception:
            pass
    out["hlo_text"] = hlo_text
    # planning wants EVERY instruction (the per-axis attribution walks
    # them); the bounded default cap is for dumped cost.json artifacts
    out["collectives"] = (
        _shard.comms_summary(hlo_text, flops=out["flops"],
                             max_instructions=65536)
        if hlo_text else None)
    out["executable"] = executable
    return out


# ---------------------------------------------------------------------------
# plan verdicts
# ---------------------------------------------------------------------------


def memory_fit(fit_bytes: Optional[float], hbm_limit_bytes: float,
               state_bytes: Optional[float] = None,
               headroom_fraction: Optional[float] = None) -> Dict[str, Any]:
    """Does the per-device program fit its stated HBM? ``fit_bytes`` is
    the donation-adjusted per-device peak from :func:`aot_analyze`;
    ``headroom_fraction`` reserves runtime slack (allocator
    fragmentation, infeed buffers) off the top — None reads the
    ``PADDLE_TPU_PLAN_HEADROOM`` registry knob (default 0.10). Verdicts:
    ``fit`` / ``tight`` (inside the limit but eating the headroom) /
    ``oom`` / ``unknown`` (no memory analysis on this backend)."""
    if headroom_fraction is None:
        headroom_fraction = float(_flags.env_flag("PADDLE_TPU_PLAN_HEADROOM"))
    limit = float(hbm_limit_bytes)
    if not fit_bytes or limit <= 0:
        return {"verdict": "unknown", "hbm_limit_bytes": int(limit),
                "per_device_bytes": None}
    usable = limit * (1.0 - headroom_fraction)
    used = float(fit_bytes)
    if used > limit:
        verdict = "oom"
    elif used > usable:
        verdict = "tight"
    else:
        verdict = "fit"
    return {
        "verdict": verdict,
        "per_device_bytes": int(used),
        "state_bytes": int(state_bytes) if state_bytes else None,
        "hbm_limit_bytes": int(limit),
        "headroom_fraction": headroom_fraction,
        "utilization": round(used / limit, 4),
    }


def axis_bytes_breakdown(collectives: Optional[dict], mesh
                         ) -> Dict[str, dict]:
    """Attribute the comms summary's collective payload bytes to mesh
    axes by matching each instruction's replica group size against the
    axis sizes (a group spanning 4 devices on a {dp:4, tp:2} mesh is dp
    traffic). Ambiguous sizes (two axes of equal size, or composite
    groups) land under a ``size=N`` key — best-effort attribution, the
    per-instruction records stay authoritative. Records carrying an
    explicit ``group_axes`` list (the recipes' ANALYTIC plan
    instructions know which axes each term spans) attribute by it
    directly — no size-matching guesswork."""
    out: Dict[str, dict] = {}
    if not collectives:
        return out
    sizes: Dict[int, List[str]] = {}
    for ax, n in mesh.shape.items():
        sizes.setdefault(int(n), []).append(str(ax))
    for rec in collectives.get("instructions", []):
        gs = rec.get("group_size")
        ga = rec.get("group_axes")
        if ga:
            key = "|".join(str(a) for a in ga) or "unattributed"
        elif gs and gs in sizes and len(sizes[gs]) == 1:
            key = sizes[gs][0]
        elif gs:
            cands = sizes.get(gs)
            key = ("|".join(cands) if cands else f"size={gs}")
        else:
            key = "unattributed"
        row = out.setdefault(key, {"count": 0, "payload_bytes": 0,
                                   "kinds": {}})
        row["count"] += 1
        row["payload_bytes"] += rec["payload_bytes"]
        row["kinds"][rec["kind"]] = row["kinds"].get(rec["kind"], 0) + 1
    return dict(sorted(out.items()))


def axis_link_classes(axes: Sequence[str], num_slices: int = 1,
                      dcn_axes: Sequence[str] = ()) -> Dict[str, str]:
    """Map each mesh axis to its link class: ``ici`` (fast intra-slice
    fabric) or ``dcn`` (the slow cross-slice/cross-host link). An axis
    is dcn when explicitly named in ``dcn_axes``, or when the topology
    describes multiple slices and the axis is the data-parallel one
    (the only axis the hybrid-layout convention routes across slices —
    fsdp/tp stay inside a slice). Composite breakdown keys ("a|b")
    price as dcn when ANY member axis is dcn — the slow link bounds the
    composite."""
    named = {str(a) for a in (dcn_axes or ())}
    out: Dict[str, str] = {}
    for ax in axes:
        ax = str(ax)
        parts = ax.split("|")
        dcn = any(p in named or
                  (int(num_slices) > 1 and p == "dp") for p in parts)
        out[ax] = "dcn" if dcn else "ici"
    return out


def roofline(flops_per_device: Optional[float],
             bytes_accessed: Optional[float],
             collective_payload_bytes: Optional[float],
             chip: Dict[str, float],
             payload_by_link_class: Optional[Dict[str, float]] = None,
             link_bandwidth: Optional[Dict[str, float]] = None
             ) -> Dict[str, Any]:
    """Roofline-style step-time estimate from the per-device analysis:
    compute time (FLOPs / peak), HBM time (bytes accessed / bandwidth),
    collective time, step estimate = max(compute, memory) + collectives
    (collectives assumed exposed — the pessimistic planning bound;
    overlap only improves on it).

    The collective term has two pricings. Flat (legacy): every payload
    byte over the ICI link bandwidth. Link-class aware: pass
    ``payload_by_link_class`` ({"ici": bytes, "dcn": bytes} — see
    :func:`axis_link_classes`) and each class's bytes price over its
    own bandwidth — the chip's ici_gbps/dcn_gbps by default, or
    ``link_bandwidth`` ({class: bytes/sec}) when a committed round's
    MEASURED commswatch table is available (planner.calibrate wires it
    through). The per-class terms land in ``comms_by_link_class``."""
    peak = chip.get("peak_flops") or 0.0
    hbm_bw = (chip.get("hbm_gbps") or 0.0) * 1e9
    ici_bw = (chip.get("ici_gbps") or 0.0) * 1e9
    compute_s = (float(flops_per_device) / peak
                 if flops_per_device and peak else None)
    memory_s = (float(bytes_accessed) / hbm_bw
                if bytes_accessed and hbm_bw else None)
    comms_by_class: Optional[Dict[str, dict]] = None
    if payload_by_link_class:
        comms_s = 0.0
        comms_by_class = {}
        for cls, nbytes in sorted(payload_by_link_class.items()):
            if not nbytes:
                continue
            bw = (link_bandwidth or {}).get(cls)
            src = "measured" if bw else "chip_spec"
            if not bw:
                bw = (chip.get(f"{cls}_gbps") or 0.0) * 1e9 or ici_bw
            t = float(nbytes) / bw if bw else 0.0
            comms_s += t
            comms_by_class[cls] = {
                "payload_bytes": float(nbytes),
                "bytes_per_sec": bw,
                "seconds": t,
                "bandwidth_source": src,
            }
    else:
        comms_s = (float(collective_payload_bytes) / ici_bw
                   if collective_payload_bytes and ici_bw else 0.0)
    known = [t for t in (compute_s, memory_s) if t is not None]
    step = (max(known) + (comms_s or 0.0)) if known else None
    bound = None
    if step:
        parts = {"compute": compute_s or 0.0, "memory": memory_s or 0.0,
                 "collective": comms_s or 0.0}
        bound = max(parts, key=parts.get)
    out = {
        "compute_seconds": compute_s,
        "memory_seconds": memory_s,
        "collective_seconds": comms_s,
        "step_seconds_estimate": step,
        "bound_by": bound,
        "chip": {k: chip.get(k) for k in ("peak_flops", "hbm_gbps",
                                          "ici_gbps", "dcn_gbps",
                                          "hbm_gb")},
    }
    if comms_by_class is not None:
        out["comms_by_link_class"] = comms_by_class
    return out
