"""Automatic mixed precision.

Counterpart of the reference AMP stack
(/root/reference/paddle/fluid/imperative/amp_auto_cast.cc, python
dygraph/amp/: auto_cast + GradScaler; static
contrib/mixed_precision/decorator.py:218). TPU-first: the low-precision
type is bfloat16, which needs NO loss scaling (same exponent range as
fp32) — GradScaler is kept API-compatible but becomes a passthrough at
scale 1.0 unless fp16 is explicitly requested.

`auto_cast` works by wrapping the tracer/lowering dtype policy: inputs of
matmul/conv-class ops are cast to bf16 (white list), reductions and
normalizations stay fp32 (black list) — the same two-list design as the
reference (fp16_utils.py:190), applied at lowering time.
"""
from __future__ import annotations

import contextlib
from typing import Optional

import numpy as np

# ops whose inputs are cast to the compute dtype (reference white list)
WHITE_LIST = {
    "conv2d", "depthwise_conv2d", "conv3d", "conv2d_transpose",
    "matmul", "matmul_v2", "mul", "bmm", "fused_attention_tpu",
}
# ops forced to run in fp32 (reference black list)
BLACK_LIST = {
    "softmax", "log_softmax", "softmax_with_cross_entropy", "cross_entropy",
    "layer_norm", "batch_norm", "group_norm", "instance_norm",
    "reduce_sum", "reduce_mean", "mean", "sum", "exp", "log",
    "squared_l2_norm", "p_norm", "frobenius_norm",
}

_amp_state = {"enabled": False, "dtype": "bfloat16", "level": "O1"}


def amp_state():
    return _amp_state


@contextlib.contextmanager
def auto_cast(enable: bool = True, custom_white_list=None, custom_black_list=None, level: str = "O1", dtype: str = "bfloat16"):
    """paddle.amp.auto_cast — toggles the lowering-time cast policy."""
    global _amp_state
    old = dict(_amp_state)
    _amp_state.update({"enabled": enable, "dtype": dtype, "level": level})
    if custom_white_list:
        _amp_state["extra_white"] = set(custom_white_list)
    if custom_black_list:
        _amp_state["extra_black"] = set(custom_black_list)
    try:
        yield
    finally:
        _amp_state.clear()
        _amp_state.update(old)


autocast = auto_cast


def amp_cast_inputs(op_type: str, ins: dict):
    """Called from lowering dispatch when AMP is on: cast white-list op
    inputs to the compute dtype."""
    import jax.numpy as jnp

    if not _amp_state["enabled"]:
        return ins
    white = WHITE_LIST | _amp_state.get("extra_white", set())
    black = BLACK_LIST | _amp_state.get("extra_black", set())
    dt = jnp.bfloat16 if _amp_state["dtype"] in ("bfloat16", "bf16") else jnp.float16
    if op_type in white:
        return {
            k: [v.astype(dt) if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating) else v for v in vs]
            for k, vs in ins.items()
        }
    if op_type in black:
        return {
            k: [v.astype(jnp.float32) if hasattr(v, "dtype") and v.dtype in (jnp.bfloat16, jnp.float16) else v for v in vs]
            for k, vs in ins.items()
        }
    return ins


class GradScaler:
    """Reference dygraph GradScaler (dygraph/amp/loss_scaler.py). With bf16
    (the TPU default) no scaling is needed; with fp16 it implements the
    reference dynamic loss scaling algorithm."""

    def __init__(
        self,
        enable: bool = True,
        init_loss_scaling: float = 2.0 ** 15,
        incr_ratio: float = 2.0,
        decr_ratio: float = 0.5,
        incr_every_n_steps: int = 1000,
        decr_every_n_nan_or_inf: int = 2,
        use_dynamic_loss_scaling: bool = True,
    ):
        self._enable = enable and _amp_state.get("dtype") == "float16"
        self._scale = init_loss_scaling if self._enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good = 0
        self._bad = 0
        self._found_inf = False

    def scale(self, loss):
        if not self._enable or self._scale == 1.0:
            return loss
        from ..ops.api import scale as _scale

        return _scale(loss, self._scale)

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        params = [p for p in (optimizer._parameter_list or []) if p.grad is not None]
        self._found_inf = False
        for p in params:
            g = p.grad.numpy()
            if not np.isfinite(g).all():
                self._found_inf = True
                break
        if self._found_inf:
            self._bad += 1
            self._good = 0
            if self._dynamic and self._bad >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad = 0
            optimizer.clear_grad()
            return
        inv = 1.0 / self._scale
        for p in params:
            p.grad._value = p.grad._value * inv
        optimizer.step()
        self._good += 1
        self._bad = 0
        if self._dynamic and self._good >= self._incr_every:
            self._scale *= self._incr_ratio
            self._good = 0

    def update(self):
        pass

    def is_enable(self):
        return self._enable

    def get_loss_scaling(self):
        return self._scale


def decorate(models=None, optimizers=None, level="O1", dtype="bfloat16", master_weight=None):
    """paddle.amp.decorate — O2 casts model params to the compute dtype."""
    if level == "O2" and models is not None:
        import jax.numpy as jnp

        dt = jnp.bfloat16 if dtype in ("bfloat16", "bf16") else jnp.float16
        model_list = models if isinstance(models, (list, tuple)) else [models]
        for m in model_list:
            for p in m.parameters():
                if hasattr(p, "_value") and jnp.issubdtype(p._value.dtype, jnp.floating):
                    p._value = p._value.astype(dt)
    if optimizers is None:
        return models
    return models, optimizers
