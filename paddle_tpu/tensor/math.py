"""reference python/paddle/tensor/math.py."""
from ..ops.api import (  # noqa: F401
    add, subtract, multiply, divide, mod, floor_divide, maximum, minimum,
    scale, clip, cumsum, sum, mean, max, min, prod,
)
from ..ops.api import pow_ as pow  # noqa: F401
from ..ops.api import _unary as __unary

abs = __unary("abs")
exp = __unary("exp")
log = __unary("log")
sqrt = __unary("sqrt")
rsqrt = __unary("rsqrt")
square = __unary("square")
sin = __unary("sin")
cos = __unary("cos")
tanh = __unary("tanh")
floor = __unary("floor")
ceil = __unary("ceil")
round = __unary("round")
sign = __unary("sign")
reciprocal = __unary("reciprocal")
