"""reference python/paddle/tensor/search.py."""
from ..ops.api import argmax, argmin, topk, where  # noqa: F401


def sort(x, axis=-1, descending=False, name=None):
    from ..ops.api import dispatch

    return dispatch("argsort", {"X": x},
                    {"axis": int(axis), "descending": bool(descending)},
                    ("Out",))


def argsort(x, axis=-1, descending=False, name=None):
    from ..ops.api import dispatch

    return dispatch("argsort", {"X": x},
                    {"axis": int(axis), "descending": bool(descending)},
                    ("Out", "Indices"))[1]


def index_select(x, index, axis=0, name=None):
    from ..ops.api import gather

    return gather(x, index, axis=axis)


def masked_select(x, mask, name=None):
    from ..ops.api import dispatch

    return dispatch("masked_select", {"X": x, "Mask": mask}, {}, ("Y",))


def nonzero(x, as_tuple=False, name=None):
    from ..ops.api import dispatch

    out = dispatch("where_index", {"Condition": x}, {}, ("Out",))
    if not as_tuple:
        return out
    n = len(out.shape) if hasattr(out, "shape") else 1
    from ..ops.api import split as _split

    return tuple(_split(out, out.shape[-1], axis=-1))
