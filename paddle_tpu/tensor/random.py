"""reference python/paddle/tensor/random.py."""


def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0, name=None):
    from ..ops.api import dispatch

    return dispatch("uniform_random", {}, {
        "shape": [int(s) for s in shape], "dtype": str(dtype),
        "min": float(min), "max": float(max), "seed": int(seed)}, ("Out",))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    from ..ops.api import dispatch

    return dispatch("gaussian_random", {}, {
        "shape": [int(s) for s in shape or []], "mean": float(mean),
        "std": float(std), "dtype": "float32", "seed": 0}, ("Out",))


def rand(shape, dtype="float32", name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def randn(shape, dtype="float32", name=None):
    return normal(0.0, 1.0, shape)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    from ..ops.api import dispatch

    if high is None:
        low, high = 0, low
    return dispatch("randint", {}, {
        "shape": [int(s) for s in shape], "low": int(low),
        "high": int(high), "dtype": str(dtype), "seed": 0}, ("Out",))


def randperm(n, dtype="int64", name=None):
    from ..ops.api import dispatch

    return dispatch("randperm", {}, {"n": int(n), "dtype": str(dtype),
                                     "seed": 0}, ("Out",))
