"""reference python/paddle/tensor/attribute.py."""


def shape(x, name=None):
    from ..ops.api import dispatch

    return dispatch("shape", {"Input": x}, {}, ("Out",))


def rank(x, name=None):
    from ..ops.api import dispatch

    return dispatch("rank", {"Input": x}, {}, ("Out",))


def real(x, name=None):
    from ..ops.api import dispatch

    return dispatch("real", {"X": x}, {}, ("Out",))


def imag(x, name=None):
    from ..ops.api import dispatch

    return dispatch("imag", {"X": x}, {}, ("Out",))
