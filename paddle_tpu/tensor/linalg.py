"""reference python/paddle/tensor/linalg.py."""
from ..ops.api import bmm, matmul  # noqa: F401


def dot(x, y, name=None):
    from ..ops.api import dispatch

    return dispatch("dot", {"X": x, "Y": y}, {}, ("Out",))


def norm(x, p=2, axis=None, keepdim=False, name=None):
    """Frobenius / p-norm via the composed ops (reference tensor/linalg.py
    norm builds the same reduce graph)."""
    from ..ops.api import sum as _sum
    from . import math as _m

    if p == 2:
        return _m.sqrt(_sum(_m.square(x), axis=axis, keepdim=keepdim))
    if p == 1:
        return _sum(_m.abs(x), axis=axis, keepdim=keepdim)
    powd = _m.pow(_m.abs(x), p)
    return _m.pow(_sum(powd, axis=axis, keepdim=keepdim), 1.0 / p)


def transpose(x, perm, name=None):
    from ..ops.api import transpose as _t

    return _t(x, perm, name)
