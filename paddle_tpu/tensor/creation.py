"""reference python/paddle/tensor/creation.py."""
from ..ops.api import (  # noqa: F401
    arange, full, ones, ones_like, zeros, zeros_like,
)


def full_like(x, fill_value, dtype=None, name=None):
    from ..ops.api import dispatch

    attrs = {"value": float(fill_value)}
    if dtype is not None:
        attrs["dtype"] = str(dtype)
    return dispatch("fill_any_like", {"X": x}, attrs, ("Out",))


def linspace(start, stop, num, dtype="float32", name=None):
    from ..ops.api import dispatch

    return dispatch("linspace", {}, {
        "start": float(start), "stop": float(stop), "num": int(num),
        "dtype": str(dtype)}, ("Out",))


def eye(num_rows, num_columns=None, dtype="float32", name=None):
    from ..ops.api import dispatch

    return dispatch("eye", {}, {
        "num_rows": int(num_rows),
        "num_columns": int(num_columns or num_rows),
        "dtype": str(dtype)}, ("Out",))
