"""reference python/paddle/tensor/stat.py."""
from ..ops.api import mean  # noqa: F401


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    from ..ops.api import dispatch

    # reference tensor/stat.py composes mean/subtract/square the same way
    from ..ops.api import mean as _mean
    from ..ops.api import multiply, subtract

    m = _mean(x, axis=axis, keepdim=True)
    d = subtract(x, m)
    v = _mean(multiply(d, d), axis=axis, keepdim=keepdim)
    if unbiased:
        import numpy as np

        shape = x.shape
        if axis is None:
            n = int(np.prod(shape))
        else:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            n = int(np.prod([shape[a] for a in axes]))
        if n > 1:
            from ..ops.api import scale as _scale

            v = _scale(v, scale=n / (n - 1))
    return v


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    from . import math as _m

    return _m.sqrt(var(x, axis, unbiased, keepdim))


def median(x, axis=None, keepdim=False, name=None):
    from ..ops.api import dispatch

    attrs = {"keep_dim": bool(keepdim)}
    if axis is not None:
        attrs["axis"] = int(axis)
    return dispatch("median", {"X": x}, attrs, ("Out",))


def numel(x, name=None):
    from ..ops.api import dispatch

    return dispatch("size", {"Input": x}, {}, ("Out",))
