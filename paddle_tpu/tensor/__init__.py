"""paddle.tensor namespace (reference python/paddle/tensor/: math,
linalg, manipulation, creation, logic, random, search, stat modules,
~7.7k LoC of thin wrappers).

The TPU build's tensor functions are the op-dispatch wrappers in
ops/api.py (one jitted lowering per op, dygraph-traced); this package
re-exports them in the reference's module layout and adds the
search/stat/random functions the flat namespace lacked. Every function
works in both dygraph (Tensor in/out) and static (Variable in/out)
mode through the same dispatch."""
from . import attribute, creation, linalg, logic, manipulation, math, random, search, stat
from .attribute import *  # noqa: F401,F403
from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
