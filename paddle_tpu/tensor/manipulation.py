"""reference python/paddle/tensor/manipulation.py."""
from ..ops.api import (  # noqa: F401
    cast, concat, expand, flatten, gather, reshape, split, squeeze, stack,
    tile, transpose, unsqueeze,
)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis)


def roll(x, shifts, axis=None, name=None):
    from ..ops.api import dispatch

    sh = shifts if isinstance(shifts, (list, tuple)) else [shifts]
    ax = axis if axis is None or isinstance(axis, (list, tuple)) else [axis]
    return dispatch("roll", {"X": x},
                    {"shifts": [int(s) for s in sh],
                     "axis": [] if ax is None else [int(a) for a in ax]},
                    ("Out",))


def flip(x, axis, name=None):
    from ..ops.api import dispatch

    ax = axis if isinstance(axis, (list, tuple)) else [axis]
    return dispatch("flip", {"X": x}, {"axis": [int(a) for a in ax]}, ("Out",))


def gather_nd(x, index, name=None):
    from ..ops.api import dispatch

    return dispatch("gather_nd", {"X": x, "Index": index}, {}, ("Out",))


def scatter(x, index, updates, overwrite=True, name=None):
    from ..ops.api import dispatch

    return dispatch("scatter", {"X": x, "Ids": index, "Updates": updates},
                    {"overwrite": bool(overwrite)}, ("Out",))
