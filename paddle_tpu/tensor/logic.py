"""reference python/paddle/tensor/logic.py."""
from ..ops.api import (  # noqa: F401
    equal, greater_equal, greater_than, less_equal, less_than,
    logical_and, logical_or, logical_xor, not_equal,
)


def logical_not(x, name=None):
    from ..ops.api import dispatch

    return dispatch("logical_not", {"X": x}, {}, ("Out",))


def is_empty(x, name=None):
    from ..ops.api import dispatch

    return dispatch("is_empty", {"X": x}, {}, ("Out",))


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    from ..ops.api import dispatch

    return dispatch("allclose", {"Input": x, "Other": y},
                    {"rtol": float(rtol), "atol": float(atol),
                     "equal_nan": bool(equal_nan)}, ("Out",))
