"""Fleet: unified distributed-training API.

Counterpart of /root/reference/python/paddle/distributed/fleet/base/
fleet_base.py:63,125,572,937 (fleet.init / distributed_optimizer /
minimize) and the meta-optimizer stack (fleet/meta_optimizers/). The
strategy object keeps the reference's protobuf field surface
(framework/distributed_strategy.proto:94-131); meta-optimizer selection is
driven by the same bits. TPU mapping: collective mode = mesh placement +
GSPMD (c_* ops are desc-level parity, SURVEY.md §5.8); a_sync/PS mode is
the host-side parameter-server path (paddle_tpu.distributed.ps).
"""
from __future__ import annotations

from .base.distributed_strategy import DistributedStrategy
from .base.role_maker import PaddleCloudRoleMaker, RoleMakerBase, UserDefinedRoleMaker

from ...parallel.env import get_rank, get_world_size, init_parallel_env

_fleet_state = {
    "initialized": False,
    "role_maker": None,
    "strategy": None,
    "is_collective": True,
}


def init(role_maker=None, is_collective: bool = True, strategy: DistributedStrategy | None = None):
    """Reference fleet_base.py:125."""
    _fleet_state["initialized"] = True
    _fleet_state["role_maker"] = role_maker or PaddleCloudRoleMaker(is_collective=is_collective)
    _fleet_state["is_collective"] = is_collective
    _fleet_state["strategy"] = strategy or DistributedStrategy()
    if get_world_size() > 1:
        init_parallel_env()


def is_first_worker() -> bool:
    return worker_index() == 0


def _ps_mode() -> bool:
    rm = _fleet_state.get("role_maker")
    return (
        not _fleet_state.get("is_collective", True)
        and rm is not None
        and bool(getattr(rm, "_server_endpoints", []))
    )


def worker_index() -> int:
    if _ps_mode():
        return _fleet_state["role_maker"].worker_index()
    return get_rank()


def worker_num() -> int:
    if _ps_mode():
        return _fleet_state["role_maker"].worker_num()
    return get_world_size()


def is_server() -> bool:
    rm = _fleet_state.get("role_maker")
    return rm is not None and rm.is_server()


def barrier_worker():
    if _ps_mode():
        from ..ps.communicator import Communicator

        Communicator.get().barrier_all()
        return
    from .. import collective

    collective.barrier()


def init_worker():
    """PS-mode trainer bring-up (reference fleet_base.py init_worker):
    connect the Communicator, seed/pull initial params."""
    t = _fleet_state.get("transpiler")
    if t is not None:
        from ...framework.scope import global_scope

        _fleet_state["communicator"] = t.init_communicator(global_scope())


def run_server():
    """PS-mode server loop (reference init_server + run_server): serve
    this role's endpoint, blocking until a trainer sends stop."""
    import os

    from ..ps.server import start_server

    t = _fleet_state.get("transpiler")
    if t is None:
        raise RuntimeError("run_server() before distributed_optimizer().minimize()")
    endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT")
    if not endpoint:
        raise RuntimeError("PADDLE_CURRENT_ENDPOINT not set for the pserver role")
    start_server(endpoint, t.get_pserver(endpoint), block=True)


def init_server(model_dir=None):
    """Parity no-op: server state lives in get_pserver()'s optimizer
    config; checkpoint loading lands with the ckpt subsystem."""


def stop_worker():
    if _ps_mode():
        from ..ps.communicator import Communicator

        try:
            comm = Communicator.get()
        except RuntimeError:
            return
        comm.barrier_all()
        if worker_index() == 0:
            comm.shutdown_servers()
        Communicator.stop()


class _FleetOptimizer:
    """distributed_optimizer(...) result: applies strategy meta-passes
    around the inner optimizer's minimize, mirroring the reference
    meta-optimizer pipeline (fleet/base/meta_optimizer_factory.py)."""

    def __init__(self, optimizer, strategy: DistributedStrategy):
        self._inner = optimizer
        self._strategy = strategy or DistributedStrategy()

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        from ...framework import program as framework

        strat = self._strategy
        inner = self._inner

        if strat.recompute:
            from .meta_optimizers import RecomputeOptimizer

            inner = RecomputeOptimizer(inner, strat.recompute_configs)
        if strat.gradient_merge:
            from .meta_optimizers import GradientMergeOptimizer

            inner = GradientMergeOptimizer(inner, strat.gradient_merge_configs)
        if strat.lamb:
            inner = _swap_to_lamb(inner, strat.lamb_configs)
        pipelined = strat.pipeline and not framework.in_dygraph_mode()
        if pipelined:
            if strat.gradient_merge:
                raise ValueError(
                    "strategy.pipeline already accumulates gradients over "
                    "num_microbatches; combining it with "
                    "strategy.gradient_merge is not supported"
                )
            from .meta_optimizers import PipelineOptimizer

            cfg = strat.pipeline_configs or {}
            # program rewrites (per-grad c_allreduce for multi-process dp)
            # must land BEFORE sectioning or the sections never run them
            hook = None
            if _fleet_state["is_collective"] and get_world_size() > 1:
                hook = lambda pg: _insert_grad_allreduce(
                    loss.block.program, pg, strategy=strat
                )
            inner = PipelineOptimizer(
                inner,
                num_microbatches=int(cfg.get("accumulate_steps", 2)),
                num_stages=(
                    strat.pipeline_parallel_degree
                    if strat.pipeline_parallel_degree > 1
                    else None
                ),
                pre_split_hook=hook,
            )

        result = inner.minimize(loss, startup_program, parameter_list, no_grad_set)
        params_grads = result[1] if isinstance(result, tuple) else result

        # GSPMD-native recipe path (parallel/recipes.py): pjit-lower the
        # whole step over one named-axis mesh instead of rewriting the
        # block with per-grad collectives. Single-controller mode only —
        # every mesh device must be addressable from this process; the
        # multi-process launcher keeps the explicit-collectives path
        # below as the fallback and the A/B baseline.
        if (
            _fleet_state["is_collective"]
            and not framework.in_dygraph_mode()
            and not pipelined
            and not _ps_mode()
            and self._recipe_name()
        ):
            if self._apply_sharding_recipe(loss.block.program):
                return result

        # PS mode (reference ParameterServerOptimizer meta pass): split
        # the program — optimizer ops move to the pservers, send/recv
        # ops take their place in the trainer program
        if _ps_mode() and not framework.in_dygraph_mode():
            from ..ps.transpiler import DistributeTranspiler

            rm = _fleet_state["role_maker"]
            t = DistributeTranspiler()
            t.transpile(
                rm.worker_index() if rm.is_worker() else 0,
                program=loss.block.program,
                pservers=",".join(rm._server_endpoints),
                trainers=rm.worker_num(),
                sync_mode=not strat.a_sync,
            )
            _fleet_state["transpiler"] = t

        # collective DP: bucketed fused all-reduce per ~bucket_mb of
        # gradients (c_allreduce_bucket; the reference transpiler's
        # per-grad c_allreduce_sum inserts are the bucket_mb=0 fallback).
        # Under the GSPMD executor these lower to identity (the reduction
        # is implied by dp-sharded feeds); under shard_map executors they
        # are real psums / quantized all-gathers. Strategy knobs
        # (dp_comms_configs: bucket_mb / overlap / quantize) select the
        # recipe; None defers to the PADDLE_TPU_DP_* env flags.
        if (
            _fleet_state["is_collective"]
            and get_world_size() > 1
            and params_grads
            and not framework.in_dygraph_mode()
            and not pipelined  # pipeline inserted it pre-split via the hook
        ):
            _insert_grad_allreduce(loss.block.program, params_grads,
                                   strategy=strat)
        return result

    def _recipe_name(self) -> str:
        """The active sharding recipe: strategy first, the
        PADDLE_TPU_SHARDING_RECIPE env knob as the unset default."""
        from ... import flags as _flags

        name = (getattr(self._strategy, "sharding_recipe", "") or "").strip()
        return name or str(
            _flags.env_flag("PADDLE_TPU_SHARDING_RECIPE")).strip()

    def _apply_sharding_recipe(self, program) -> bool:
        """Attach the resolved recipe's mesh + sharding rules to the
        program (executor then compiles the step with recipe-derived
        in/out shardings and GSPMD-placed collectives). Returns False —
        falling back to the explicit-collectives rewrite — when this
        process is not a single controller over >1 device."""
        import warnings

        import jax

        from ...parallel import recipes as _recipes

        name = self._recipe_name()
        ndev = len(jax.devices())
        if get_world_size() > 1:
            warnings.warn(
                f"sharding_recipe={name!r} needs a single controller "
                f"over all mesh devices; this process is rank "
                f"{get_rank()} of {get_world_size()} — falling back to "
                f"explicit per-grad collectives")
            return False
        if ndev < 2:
            return False  # one device: nothing to lay out
        resolved = _recipes.resolve_recipe(
            name, ndev,
            overrides=getattr(self._strategy,
                              "sharding_recipe_configs", None))
        _recipes.apply_to_program(program, resolved)
        return True

    def step(self):
        self._inner.step()
        # dygraph DP: average grads across trainers before the update
        if _fleet_state["is_collective"] and get_world_size() > 1:
            pass  # grads already reduced in backward hook / DataParallel

    def clear_grad(self):
        self._inner.clear_grad()


_OPTIMIZER_OPS = (
    "sgd", "momentum", "adam", "adamw", "lamb", "lars_momentum",
    "adagrad", "rmsprop", "adamax", "adadelta", "ftrl",
)


def _insert_grad_allreduce(program, params_grads, strategy=None):
    """Rewrite the program for multi-process DP: coalesce the gradients
    into deterministic byte buckets (reverse build order — the order the
    backward produces them) and insert ONE fused c_allreduce_bucket per
    bucket. With overlap on, each bucket lands immediately AFTER the op
    producing its last gradient, so XLA's scheduler is free to run the
    collective concurrently with the remaining backward ops (TACCL's
    point: schedule collectives deliberately, not in declaration order);
    overlap off (or the legacy bucket_mb=0) packs them just before the
    optimizer ops. The 1/nranks average folds into the op's scale attr."""
    from .. import comms

    block = program.global_block()
    nranks = get_world_size()
    cfg = dict(getattr(strategy, "dp_comms_configs", None) or {})
    mb = cfg.get("bucket_mb")
    mb = comms.bucket_mb() if mb is None else float(mb)
    overlap = cfg.get("overlap")
    overlap = comms.overlap_enabled() if overlap is None else bool(overlap)
    quantize = cfg.get("quantize")
    quantize = comms.quantize_mode() if quantize is None else (
        quantize or "none")

    pgs = [(p, g) for p, g in params_grads if g is not None]
    if not pgs:
        return

    # first optimizer op = the barrier no collective may cross
    first_opt = next((i for i, op in enumerate(block.ops)
                      if op.type in _OPTIMIZER_OPS), len(block.ops))

    if mb <= 0:
        # legacy recipe: one c_allreduce_sum + scale per gradient, each
        # just before the first optimizer op (desc parity with the
        # reference transpiler collective.py:178)
        for p, g in reversed(pgs):
            block._insert_op(
                first_opt, "c_allreduce_sum",
                inputs={"X": [g]}, outputs={"Out": [g]},
                attrs={"ring_id": 0},
            )
            block._insert_op(
                first_opt + 1, "scale",
                inputs={"X": [g]}, outputs={"Out": [g]},
                attrs={"scale": 1.0 / nranks, "bias": 0.0,
                       "bias_after_scale": True},
            )
        return

    grads = {g.name: g for _, g in pgs}
    buckets = comms.assign_buckets(
        [(g.name, tuple(g.shape), str(g.dtype)) for _, g in pgs],
        int(mb * 1024 * 1024))

    # last op writing each gradient, on the PRE-insert op list
    last_writer = {}
    for i, op in enumerate(block.ops[:first_opt]):
        for name in op.output_arg_names():
            if name in grads:
                last_writer[name] = i
    plans = []
    for b in buckets:
        if overlap:
            pos = 1 + max((last_writer.get(n, first_opt - 1)
                           for n in b.names), default=first_opt - 1)
            pos = min(pos, first_opt)
        else:
            pos = first_opt
        plans.append((pos, b))
    # insert bottom-up so earlier positions stay valid
    for pos, b in sorted(plans, key=lambda x: x[0], reverse=True):
        bucket_grads = [grads[n] for n in b.names]
        block._insert_op(
            pos, "c_allreduce_bucket",
            inputs={"X": bucket_grads}, outputs={"Out": bucket_grads},
            attrs={"ring_id": 0, "scale": 1.0 / nranks,
                   "quantize": quantize or "none",
                   "block_size": comms.quant_block()},
        )


def _swap_to_lamb(optimizer, configs):
    from ...optimizer import Lamb

    return Lamb(
        learning_rate=optimizer.get_lr(),
        lamb_weight_decay=configs.get("lamb_weight_decay", 0.01),
        parameters=getattr(optimizer, "_parameter_list", None),
    )


def distributed_optimizer(optimizer, strategy: DistributedStrategy | None = None):
    """Reference fleet_base.py:572."""
    return _FleetOptimizer(optimizer, strategy or _fleet_state["strategy"])

from . import metrics  # noqa: E402,F401
