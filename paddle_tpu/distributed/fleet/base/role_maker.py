"""Role makers: who am I in the cluster.

Counterpart of /root/reference/python/paddle/distributed/fleet/base/
role_maker.py (PaddleCloudRoleMaker reads the PADDLE_* env protocol set by
the launcher; UserDefinedRoleMaker takes explicit ranks). The same env
protocol is honored (launch_utils.py:409-440); rendezvous is the JAX
coordination service instead of gRPC NCCL-id broadcast.
"""
from __future__ import annotations

import os
from enum import Enum
from typing import List, Optional


class Role(Enum):
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER

    def is_worker(self) -> bool:
        return self._role == Role.WORKER

    def is_server(self) -> bool:
        return self._role == Role.SERVER

    def is_first_worker(self) -> bool:
        return self.is_worker() and self.worker_index() == 0

    def worker_index(self) -> int:
        raise NotImplementedError

    def worker_num(self) -> int:
        raise NotImplementedError


class PaddleCloudRoleMaker(RoleMakerBase):
    """Env-driven role maker (reference role_maker.py PaddleCloudRoleMaker)."""

    def __init__(self, is_collective: bool = True, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        training_role = os.environ.get("TRAINING_ROLE", "TRAINER")
        self._role = Role.SERVER if training_role == "PSERVER" else Role.WORKER
        self._worker_index = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._worker_num = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = eps.split(",") if eps else []
        pseps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_endpoints = pseps.split(",") if pseps else []

    def worker_index(self) -> int:
        return self._worker_index

    def worker_num(self) -> int:
        return self._worker_num

    def get_trainer_endpoints(self) -> List[str]:
        return self._worker_endpoints

    def get_pserver_endpoints(self) -> List[str]:
        return self._server_endpoints

    def server_num(self) -> int:
        return len(self._server_endpoints)

    def server_index(self) -> int:
        return int(os.environ.get("PADDLE_PORT_INDEX", os.environ.get("POD_INDEX", "0")))


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(
        self,
        current_id: int = 0,
        role: Role = Role.WORKER,
        worker_num: int = 1,
        server_endpoints: Optional[List[str]] = None,
    ):
        super().__init__()
        self._role = role
        self._worker_index = current_id
        self._worker_num = worker_num
        self._server_endpoints = server_endpoints or []

    def worker_index(self) -> int:
        return self._worker_index

    def worker_num(self) -> int:
        return self._worker_num

    def get_pserver_endpoints(self) -> List[str]:
        return self._server_endpoints
