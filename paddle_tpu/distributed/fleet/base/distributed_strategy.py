"""DistributedStrategy: declarative training-strategy config.

Counterpart of /root/reference/paddle/fluid/framework/
distributed_strategy.proto:94-131 and its Python wrapper
fleet/base/distributed_strategy.py — the same strategy bits (amp,
recompute, gradient_merge, localsgd, dgc, pipeline, a_sync, lamb, lars,
sharding + nested per-feature config dicts), driving meta-optimizer
selection. TPU additions (SURVEY.md §5.7): mesh_shape / sequence_parallel /
context_parallel bits for the sharding strategies the reference lacks.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


class DistributedStrategy:
    def __init__(self):
        # reference proto fields (distributed_strategy.proto:94-131)
        self.amp = False
        self.amp_configs: Dict = {
            "init_loss_scaling": 32768.0,
            "incr_every_n_steps": 1000,
            "decr_every_n_nan_or_inf": 2,
            "incr_ratio": 2.0,
            "decr_ratio": 0.5,
            "use_dynamic_loss_scaling": True,
            "custom_white_list": [],
            "custom_black_list": [],
        }
        self.recompute = False
        self.recompute_configs: Dict = {"checkpoints": []}
        self.gradient_merge = False
        self.gradient_merge_configs: Dict = {"k_steps": 1, "avg": True}
        self.localsgd = False
        self.localsgd_configs: Dict = {"k_steps": 1}
        self.dgc = False
        self.dgc_configs: Dict = {"rampup_begin_step": 0}
        self.pipeline = False
        self.pipeline_configs: Dict = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.a_sync = False
        self.a_sync_configs: Dict = {"k_steps": 0}
        self.lamb = False
        self.lamb_configs: Dict = {"lamb_weight_decay": 0.01, "exclude_from_weight_decay": []}
        self.lars = False
        self.lars_configs: Dict = {"lars_coeff": 0.001, "lars_weight_decay": 0.0005}
        self.sharding = False
        self.sharding_configs: Dict = {"sharding_degree": 1}
        self.nccl_comm_num = 1
        self.sync_batch_norm = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        # DP grad-sync recipe (distributed/comms.py): bucket size, whether
        # buckets are placed right after their last grad producer so XLA
        # can overlap them with the remaining backward, and the wire
        # encoding ("int8" = blockwise-quantized all-reduce). None values
        # defer to the PADDLE_TPU_DP_* env knobs.
        self.dp_comms_configs: Dict = {
            "bucket_mb": None, "overlap": None, "quantize": None,
        }
        # GSPMD-native sharding recipe (parallel/recipes.py): "" keeps
        # the explicit-collectives path; "dp"/"fsdp"/"tp"/hybrid names
        # pjit-lower the whole step over one named-axis mesh with in/out
        # shardings from the recipe (single-controller mode — every mesh
        # device addressable from this process). The configs dict
        # overrides preset axis sizes, e.g. {"tp": 4}. Unset ("") also
        # defers to the PADDLE_TPU_SHARDING_RECIPE env knob.
        self.sharding_recipe: str = ""
        self.sharding_recipe_configs: Dict = {}
        self.execution_strategy = None
        self.build_strategy = None
        self.elastic = False
        self.auto = False

        # TPU-native strategy bits (green-field, SURVEY.md §5.7):
        # mesh axes for dp/tensor/pipeline/sequence/expert parallelism
        self.mesh_shape: Dict[str, int] = {}
        self.sequence_parallel = False
        self.context_parallel_degree = 1
        self.tensor_parallel_degree = 1
        self.pipeline_parallel_degree = 1

    def __repr__(self):
        bits = [
            k for k in (
                "amp", "recompute", "gradient_merge", "localsgd", "dgc",
                "pipeline", "a_sync", "lamb", "lars", "sharding",
                "sequence_parallel", "sharding_recipe",
            ) if getattr(self, k)
        ]
        return f"DistributedStrategy({', '.join(bits) or 'default'})"
