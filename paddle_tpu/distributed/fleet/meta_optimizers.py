"""Meta-optimizers: strategy-driven wrappers around a base optimizer.

Counterpart of /root/reference/python/paddle/distributed/fleet/
meta_optimizers/ (gradient_merge_optimizer.py, recompute_optimizer.py:18,
localsgd_optimizer.py:23) and fluid GradientMergeOptimizer
(optimizer.py:4994) / RecomputeOptimizer (optimizer.py:4518).

TPU translation notes:
- GradientMerge (static): the reference wraps the update in a
  conditional_block. XLA dislikes rare branches around big ops, so here the
  update is computed every step and *gated*: each optimizer-op output o is
  rewritten to where(boundary, o, old_o), and gradients feed from a
  persistable accumulator. State transitions are identical to the
  reference's (non-boundary steps leave params/moments untouched), at the
  cost of optimizer FLOPs (negligible next to fwd/bwd) instead of a branch.
- Recompute: desc-level segment recomputation
  (framework/backward.py append_backward_with_checkpoints) — forward
  segments between checkpoints are re-emitted before their grad ops and
  fenced by `recompute_barrier` ops so XLA neither CSE-folds the clones
  nor schedules them early; measured ~4.6x activation-memory reduction
  on the 12-layer GPT flagship at seq 1024.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class GradientMergeOptimizer:
    """k-step gradient accumulation before each real update."""

    def __init__(self, inner, configs: Optional[Dict] = None):
        self._inner = inner
        cfg = configs or {}
        self.k_steps = int(cfg.get("k_steps", 1))
        self.avg = bool(cfg.get("avg", True))
        # dygraph state
        self._step_count = 0

    def __getattr__(self, item):
        return getattr(self._inner, item)

    # -- dygraph path ---------------------------------------------------
    def step(self):
        self._step_count += 1
        if self._step_count % self.k_steps == 0:
            if self.avg and self.k_steps > 1:
                for p in getattr(self._inner, "_parameter_list", []) or []:
                    if p.grad is not None:
                        p.grad._value = p.grad._value / self.k_steps
            self._inner.step()
            self._inner.clear_grad()

    def clear_grad(self):
        pass  # grads accumulate across micro-steps by design

    # -- static path ----------------------------------------------------
    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        from ...framework import program as framework

        if framework.in_dygraph_mode():
            params_grads = self._inner.backward(loss, parameter_list=parameter_list)
            self.step()
            return None, params_grads

        opt_ops, params_grads = self._inner.minimize(
            loss, startup_program, parameter_list, no_grad_set
        )
        if self.k_steps > 1:
            self._rewrite_static(loss.block.program, startup_program, params_grads)
        return opt_ops, params_grads

    def _rewrite_static(self, program, startup_program, params_grads):
        from ...framework import program as framework
        from ...framework.initializer import ConstantInitializer

        block = program.global_block()
        k = float(self.k_steps)

        # persistable step counter + per-grad accumulators
        def make_persistable(name, shape, dtype, value):
            v = block.create_var(
                name=name, shape=shape, dtype=dtype, persistable=True,
                stop_gradient=True,
            )
            ConstantInitializer(value)(v)
            return v

        counter = make_persistable("@GradientMerge.step", [1], "float32", 0.0)

        opt_types = {
            "sgd", "momentum", "adam", "adamw", "lamb", "lars_momentum",
            "adagrad", "rmsprop", "adamax", "adadelta", "ftrl",
        }
        first_opt_idx = next(
            (i for i, op in enumerate(block.ops) if op.type in opt_types),
            len(block.ops),
        )

        # build the merge prologue at the first optimizer op:
        #   step += 1 ; boundary = (step % k == 0)
        insert = first_opt_idx

        def ins_op(type_, inputs, outputs, attrs=None):
            nonlocal insert
            block._insert_op(insert, type_, inputs=inputs, outputs=outputs, attrs=attrs or {})
            insert += 1

        ins_op("increment", {"X": [counter]}, {"Out": [counter]}, {"step": 1.0})
        stepmod = block.create_var(name="@GradientMerge.stepmod", shape=[1], dtype="float32")
        kconst = block.create_var(name="@GradientMerge.k", shape=[1], dtype="float32")
        ins_op("fill_constant", {}, {"Out": [kconst]}, {"shape": [1], "value": k, "dtype": "float32"})
        ins_op("elementwise_mod", {"X": [counter], "Y": [kconst]}, {"Out": [stepmod]}, {"axis": -1})
        boundary = block.create_var(name="@GradientMerge.boundary", shape=[1], dtype="bool")
        zero = block.create_var(name="@GradientMerge.zero", shape=[1], dtype="float32")
        ins_op("fill_constant", {}, {"Out": [zero]}, {"shape": [1], "value": 0.0, "dtype": "float32"})
        ins_op("equal", {"X": [stepmod], "Y": [zero]}, {"Out": [boundary]}, {"axis": -1})

        grad_to_acc = {}
        for p, g in params_grads:
            if g is None:
                continue
            acc = make_persistable(f"{g.name}@GradientMerge", list(g.shape), g.dtype, 0.0)
            ins_op("elementwise_add", {"X": [acc], "Y": [g]}, {"Out": [acc]}, {"axis": -1})
            eff = block.create_var(name=f"{g.name}@GradientMerge.eff", shape=list(g.shape), dtype=g.dtype)
            scale = 1.0 / k if self.avg else 1.0
            ins_op("scale", {"X": [acc]}, {"Out": [eff]}, {"scale": scale, "bias": 0.0, "bias_after_scale": True})
            grad_to_acc[g.name] = (acc, eff, boundary)

        # rewire each optimizer op to read the merged grad and gate its
        # outputs on `boundary`
        i = insert
        while i < len(block.ops):
            op = block.ops[i]
            if op.type not in opt_types:
                i += 1
                continue
            gname = next(
                (n for pv in op.desc.inputs if pv.parameter == "Grad" for n in pv.arguments),
                None,
            )
            if gname not in grad_to_acc:
                i += 1
                continue
            acc, eff, bnd = grad_to_acc[gname]
            # swap Grad arg
            for pv in op.desc.inputs:
                if pv.parameter == "Grad":
                    del pv.arguments[:]
                    pv.arguments.append(eff.name)
            op._input_vars["Grad"] = [eff]
            # save old values, then gate each output
            out_vars = [v for vs in op._output_vars.values() for v in vs]
            saves = []
            for v in out_vars:
                old = block.create_var(
                    name=f"{v.name}@GradientMerge.old", shape=list(v.shape), dtype=v.dtype
                )
                block._insert_op(i, "assign", inputs={"X": [v]}, outputs={"Out": [old]})
                saves.append((v, old))
                i += 1
            i += 1  # past the optimizer op itself
            for v, old in saves:
                block._insert_op(
                    i, "where",
                    inputs={"Condition": [bnd], "X": [v], "Y": [old]},
                    outputs={"Out": [v]},
                )
                i += 1
            # reset the accumulator after a boundary update
            zacc = block.create_var(
                name=f"{acc.name}.zeroed", shape=list(acc.shape), dtype=acc.dtype
            )
            block._insert_op(i, "fill_zeros_like", inputs={"X": [acc]}, outputs={"Out": [zacc]})
            i += 1
            block._insert_op(
                i, "where",
                inputs={"Condition": [bnd], "X": [zacc], "Y": [acc]},
                outputs={"Out": [acc]},
            )
            i += 1


class RecomputeOptimizer:
    """Activation recomputation (reference optimizer.py:4518
    RecomputeOptimizer + backward.py _append_backward_ops_with_checkpoints_).

    Static path: `minimize` builds the backward with
    `append_backward_with_checkpoints` — between user-designated
    checkpoint activations, forward segments are re-emitted before their
    grad ops and fenced with `recompute_barrier` so XLA actually
    rematerializes instead of CSE-ing the clones away. Only the
    checkpoint activations stay live across the forward/backward gap."""

    def __init__(self, inner, configs: Optional[Dict] = None):
        self._inner = inner
        self._checkpoints = list((configs or {}).get("checkpoints", []))

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = list(checkpoints)

    def backward(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        """Checkpointed backward — also the composition point for outer
        meta-optimizers (PipelineOptimizer calls inner.backward, so
        recompute survives under pipeline instead of silently degrading
        to the plain backward)."""
        if not self._checkpoints:
            return self._inner.backward(
                loss, startup_program, parameter_list, no_grad_set
            )
        from ...framework.backward import append_backward_with_checkpoints

        return append_backward_with_checkpoints(
            loss,
            self._checkpoints,
            parameter_list=parameter_list or getattr(self._inner, "_parameter_list", None),
            no_grad_set=no_grad_set,
        )

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        if not self._checkpoints:
            return self._inner.minimize(loss, startup_program, parameter_list, no_grad_set)
        params_grads = self.backward(loss, startup_program, parameter_list, no_grad_set)
        self._inner.apply_gradients(params_grads)
        return None, params_grads


class PipelineOptimizer:
    """Pipeline-parallel training (reference
    python/paddle/fluid/optimizer.py:3666 PipelineOptimizer +
    framework/section_worker.cc:107-174 SectionWorker).

    The reference splits the program by `device_guard` tags into
    per-device sections, spawns one SectionWorker thread per stage, and
    runs `num_microbatches` forwards then backwards then the optimizer,
    filtering ops by role. Here `minimize` appends the backward +
    optimizer ops as usual, then calls
    `paddle_tpu.parallel.pipeline.split_program` to section the block by
    stage/phase and attaches the resulting `PipelineMeta` to the program;
    `framework.executor.Executor._run_pipeline` then executes the
    F-then-B microbatch schedule with per-stage jitted XLA programs
    pinned to distinct devices."""

    def __init__(
        self,
        inner,
        num_microbatches: int = 2,
        num_stages: Optional[int] = None,
        pre_split_hook=None,
        schedule: str = "1F1B",
    ):
        self._inner = inner
        self._num_microbatches = int(num_microbatches)
        self._num_stages = num_stages
        self._schedule = schedule
        # callback(params_grads) run after apply_gradients but BEFORE
        # sectioning — program rewrites done here (e.g. fleet's per-grad
        # c_allreduce insertion for multi-process dp x pp) land inside
        # the sections instead of being silently dropped
        self._pre_split_hook = pre_split_hook

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        from ...parallel.pipeline import split_program, stage_of_tag

        program = loss.block.program
        block = program.global_block()

        tags = [
            stage_of_tag(op.all_attrs().get("op_device", "")) for op in block.ops
        ]
        explicit = [t for t in tags if t is not None]
        num_stages = self._num_stages or (max(explicit) + 1 if explicit else 1)
        if num_stages < 2:
            raise ValueError(
                "PipelineOptimizer needs >= 2 stages; tag forward ops with "
                "device_guard('tpu:<stage>') or pass num_stages"
            )

        # AMP-style inners rewrite the forward (cast insertion + scaled
        # loss); that must happen BEFORE the forward op range is captured
        # or the inserted casts would be sectioned as backward ops
        orig_loss_name = loss.name
        rewrite = getattr(self._inner, "rewrite_forward", None)
        if rewrite is not None:
            loss = rewrite(loss)

        n_fwd_ops = len(block.ops)
        # raw backward grads are the microbatch-accumulation boundary;
        # decay/clip run once per step on the averaged grad (optimize phase)
        params_grads = self._inner.backward(
            loss, startup_program, parameter_list, no_grad_set
        )
        n_bwd_ops = len(block.ops)
        self._inner.apply_gradients(params_grads)
        if self._pre_split_hook is not None:
            self._pre_split_hook(params_grads)

        meta = split_program(
            program, num_stages, n_fwd_ops, n_bwd_ops, params_grads, loss,
            keep_vars={orig_loss_name},
        )
        meta.num_microbatches = self._num_microbatches
        meta.schedule = self._schedule
        program._pipeline_meta = meta
        return None, params_grads


class LocalSGDOptimizer:
    """Periodic parameter averaging (reference localsgd_optimizer.py:23):
    run k local steps, then all-reduce-average parameters across trainers."""

    def __init__(self, inner, configs: Optional[Dict] = None):
        self._inner = inner
        self.k_steps = int((configs or {}).get("k_steps", 1))
        self._step_count = 0

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def step(self):
        from .. import collective
        from ...parallel.env import get_world_size

        self._inner.step()
        self._step_count += 1
        n = get_world_size()
        if n > 1 and self._step_count % self.k_steps == 0:
            for p in getattr(self._inner, "_parameter_list", []) or []:
                collective.all_reduce(p)
                p._value = p._value / n


class ShardingOptimizer:
    """ZeRO/FSDP-style sharding (SURVEY §2.9 plans it as a first-class
    strategy; the reference snapshot predates its sharding optimizer).
    minimize() runs the inner optimizer, then registers GSPMD sharding
    rules on the program by `stage`:

      stage 1 (ZeRO-1): optimizer ACCUMULATORS (adam moments, ...) shard
        dim 0 over `sharding_axis`; params stay replicated.
      stage 2 (ZeRO-2): + gradient vars (`*@GRAD`) get a
        with_sharding_constraint pinning dim 0 to the axis, so the grad
        reduction compiles to reduce-scatter + sharded update + gather
        instead of all-reduce.
      stage 3 (ZeRO-3 / FSDP): + PARAMETERS shard dim 0; GSPMD inserts
        the gather-at-use in forward/backward and params+states+grads
        are all 1/n per device.

    shard_scope applies the scope rules when it lands on the mesh; XLA
    derives every collective — no manual c_* ops."""

    _STATE_SLOTS = ("Moment", "Moment1", "Moment2", "Velocity", "MeanSquare",
                    "MeanGrad", "InfNorm", "SquaredAccumulator",
                    "LinearAccumulator", "AvgSquaredGrad", "AvgSquaredUpdate")
    _OPT_TYPES = {
        "sgd", "momentum", "adam", "adamw", "lamb", "lars_momentum",
        "adagrad", "rmsprop", "adamax", "adadelta", "ftrl",
        "decayed_adagrad", "proximal_adagrad",
    }

    def __init__(self, inner, configs: Optional[Dict] = None):
        self._inner = inner
        cfg = configs or {}
        self._axis = cfg.get("sharding_axis", "dp")
        self._stage = int(cfg.get("stage", 1))

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        import re

        ops, params_grads = self._inner.minimize(
            loss, startup_program, parameter_list, no_grad_set
        )
        program = loss.block.program
        block = program.global_block()
        state_names = []
        param_names = []
        for op in block.ops:
            if op.type not in self._OPT_TYPES:
                continue
            for pv in op.desc.inputs:
                if pv.parameter in self._STATE_SLOTS:
                    for n in pv.arguments:
                        if n not in state_names:
                            state_names.append(n)
                elif pv.parameter == "Param":
                    for n in pv.arguments:
                        if n not in param_names:
                            param_names.append(n)
        rules = [(re.escape(n), (self._axis,)) for n in state_names]
        if self._stage >= 3:
            rules += [(re.escape(n), (self._axis,)) for n in param_names]
        program._sharding_rules = getattr(program, "_sharding_rules", []) + rules
        if self._stage >= 2:
            # exact parameter-grad names only: a catch-all .*@GRAD rule
            # would also pin every ACTIVATION grad's dim 0, inserting
            # reshards GSPMD would never choose
            cons = getattr(program, "_var_sharding_constraints", [])
            program._var_sharding_constraints = cons + [
                (re.escape(g.name), (self._axis,))
                for _, g in params_grads if g is not None
            ]
        self._state_names = state_names
        self._param_names = param_names
        return ops, params_grads
