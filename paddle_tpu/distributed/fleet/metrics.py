"""Global (all-trainer) metrics.

Counterpart of /root/reference/python/paddle/distributed/fleet/metrics/
metric.py (sum/max/min/auc/acc: gloo/fleet allreduce of each trainer's
local counters so every worker reports the JOB-level metric, not its
shard's). Transport here is whichever backend the job already has:

* a live PS Communicator -> counters accumulate on pserver 0 under a
  named slot and a barrier makes the reduction step-consistent (the
  reference's fleet._role_maker._all_reduce path);
* otherwise jax.distributed collectives when world_size > 1;
* single process -> identity.
"""
from __future__ import annotations

from typing import Optional

import numpy as np


def _ps_comm():
    from ..ps.communicator import Communicator

    return Communicator._instance


def _all_reduce(value: np.ndarray, op: str = "sum") -> np.ndarray:
    value = np.asarray(value, np.float64)
    comm = _ps_comm()
    if comm is not None and comm.num_trainers > 1:
        # pserver-mediated reduction: every trainer pushes into a metric
        # slot; barrier; pull the reduced value (reference metric.py uses
        # the fleet util allreduce the same way)
        name = f"@METRIC.{op}"
        ep = comm.endpoints[0]
        comm.clients[ep].call(
            "metric_push", name=name, value=value.ravel(), op=op,
            num_trainers=comm.num_trainers,
        )
        comm.barrier_all()
        out = comm.clients[ep].call("metric_pull", name=name)["value"]
        comm.barrier_all()
        return np.asarray(out, np.float64).reshape(value.shape)

    import jax

    if jax.process_count() > 1:
        import jax.numpy as jnp

        # collective._process_allgather, not multihost_utils directly:
        # it carries the coordination-KV fallback for backends that
        # reject multiprocess XLA programs (CPU-simulation runs)
        from .. import collective as _collective

        gathered = _collective._process_allgather(jnp.asarray(value))
        if op == "sum":
            return np.asarray(gathered).sum(axis=0)
        if op == "max":
            return np.asarray(gathered).max(axis=0)
        if op == "min":
            return np.asarray(gathered).min(axis=0)
    return value


def sum(input, scope=None, util=None):  # noqa: A001 (reference name)
    return _all_reduce(np.asarray(input), "sum")


def max(input, scope=None, util=None):  # noqa: A001
    return _all_reduce(np.asarray(input), "max")


def min(input, scope=None, util=None):  # noqa: A001
    return _all_reduce(np.asarray(input), "min")


def acc(correct, total, scope=None, util=None) -> float:
    """Global accuracy = sum(correct) / sum(total) over all trainers."""
    c = _all_reduce(np.asarray(correct, np.float64), "sum")
    t = _all_reduce(np.asarray(total, np.float64), "sum")
    return float(c / np.maximum(t, 1e-12))


def auc(stat_pos, stat_neg, scope=None, util=None) -> float:
    """Global AUC from summed per-bucket positive/negative counters
    (reference metric.py auc: allreduce the stat arrays, then the same
    trapezoid walk every trainer runs locally)."""
    pos = _all_reduce(np.asarray(stat_pos, np.float64), "sum")
    neg = _all_reduce(np.asarray(stat_neg, np.float64), "sum")
    # walk buckets from high score to low accumulating TPR/FPR area
    tot_pos = new_pos = 0.0
    tot_neg = new_neg = 0.0
    area = 0.0
    for i in range(len(pos) - 1, -1, -1):
        new_pos = tot_pos + pos[i]
        new_neg = tot_neg + neg[i]
        area += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
        tot_pos, tot_neg = new_pos, new_neg
    if tot_pos == 0 or tot_neg == 0:
        return 0.5
    return float(area / (tot_pos * tot_neg))
