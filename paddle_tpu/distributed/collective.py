"""paddle.distributed collective API.

Counterpart of /root/reference/python/paddle/distributed/collective.py:59-419
(all_reduce/all_gather/broadcast/reduce/scatter/barrier built on c_* NCCL
ops). Two TPU-native execution paths replace the NCCL rings:

1. **In-program (static / jit)**: placement-first. Sharded parameters and
   batches let XLA/GSPMD derive the collectives; the c_* ops lower to
   `lax.p*` only when traced inside `shard_map` (manual-SPMD regions, e.g.
   sync_batch_norm), and to identity under plain GSPMD jit, where the
   equivalent reduction is already implied by shardings (SURVEY.md §5.8).
2. **Eager (dygraph)**: cross-process collectives over the JAX distributed
   runtime (one process per TPU host), via the global-array trick:
   all-reduce = all_gather over processes + local reduction. With one
   process they are identities, matching reference world_size==1 behavior.
"""
from __future__ import annotations

import contextlib
import json
import os
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import chaos as _chaos
from .. import flags as _flags
from .. import goodput as _goodput
from .. import monitor as _monitor
from .. import profiler as _profiler

# per-collective call counts and WIRE bytes — what this rank actually
# contributes to the network per call (the quantized payload + scales in
# int8 mode, the packed fp32 buffer for exact buckets), world-size
# independent. collective_logical_bytes_total carries the fp32-equivalent
# size of the same payloads, so quantized-vs-exact compression is
# auditable from any metrics snapshot (obs_report's comms section).
_M_COLL = _monitor.counter(
    "collective_calls_total", "collective API invocations", ("op",))
_M_COLL_B = _monitor.counter(
    "collective_bytes_total",
    "local WIRE payload bytes per collective (post-quantization)", ("op",))
_M_COLL_LB = _monitor.counter(
    "collective_logical_bytes_total",
    "logical (fp32-equivalent) payload bytes per collective", ("op",))
_M_COLL_UNAVAIL = _monitor.counter(
    "collective_unavailable_total",
    "collective exchanges surfaced as typed Unavailable", ("reason",))


@contextlib.contextmanager
def _collective_window(op_name: str, value=None):
    """Count + span + goodput attribution around one collective: the
    host-blocking wall time of the call is the per-collective time
    budget (EQuARX-style accounting) and the 'collective' badput bucket
    of the step it stalls. Also a chaos site pair: an armed
    collective_delay/collective_abort fires here, before any payload
    moves. The same (op, bytes, wall) triple feeds the interconnect
    ledger (commswatch): the eager cross-process path is the harness's
    dcn-proxy link class, so every call here grows its measured
    bandwidth table for free."""
    nbytes = _record_collective(op_name, value)
    _chaos.delay(where=op_name)
    _chaos.abort(where=op_name)
    t0 = time.perf_counter()
    with _profiler.span(f"collective/{op_name}", cat="collective"):
        try:
            yield
        finally:
            elapsed = time.perf_counter() - t0
            _goodput.add("collective", elapsed)
            try:
                from .. import commswatch as _commswatch

                _commswatch.record_collective(op_name, nbytes, elapsed)
            except Exception:
                pass  # the comms ledger must never break a collective


def _value_nbytes(value) -> int:
    # size from metadata, never a device conversion: dygraph Tensors
    # expose their jax array via _value, arrays expose nbytes
    v = getattr(value, "_value", value)
    nbytes = getattr(v, "nbytes", None)
    if nbytes is None:
        nbytes = int(np.asarray(v).nbytes)
    return int(nbytes)


def _record_collective(op_name: str, value=None,
                       nbytes: Optional[int] = None,
                       logical_nbytes: Optional[int] = None
                       ) -> Optional[int]:
    """Count one collective. For plain API calls the tensor IS the wire
    payload (``value``); the bucketed/quantized paths pass the true wire
    byte count explicitly (``nbytes``) plus the fp32-equivalent
    (``logical_nbytes``) so the byte series never reports a logical fp32
    tensor the wire never carried. Returns the wire byte count (the
    commswatch bandwidth feed needs it alongside the measured wall)."""
    if not _monitor.enabled():
        return None
    _M_COLL.labels(op=op_name).inc()
    if nbytes is None and value is not None:
        nbytes = _value_nbytes(value)
    if nbytes is not None:
        _M_COLL_B.labels(op=op_name).inc(float(nbytes))
        _M_COLL_LB.labels(op=op_name).inc(
            float(logical_nbytes if logical_nbytes is not None else nbytes))
    return nbytes


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3


def _nproc() -> int:
    return jax.process_count()


def _eager_value(t):
    from ..dygraph.varbase import Tensor

    if isinstance(t, Tensor):
        return t._value
    return jnp.asarray(t)


def _wrap_like(t, val):
    from ..dygraph.varbase import Tensor

    if isinstance(t, Tensor):
        t._value = val
        return t
    return Tensor(val)


# host-side allgather fallback over the jax coordination-service KV
# store: some backends (the CPU simulator this repo tests multi-process
# on) reject multiprocess XLA computations outright, which kills
# multihost_utils.process_allgather at compile time. The rendezvous
# service itself still works, so eager collectives fall back to moving
# the (host-sized) payloads through it. The failed attempt is
# compile-local — every rank fails identically before any cross-rank
# exchange — so flipping to the fallback is rank-consistent.
_KV_FALLBACK = False
_AG_SEQ = iter(range(1 << 62))
# bounded-wait slice: between slices a blocked rank polls the failure
# epoch, so ONE rank's timeout verdict aborts every survivor's in-flight
# exchange instead of each serially burning its own full deadline
_KV_POLL_MS = 500


def _coll_timeout_ms() -> int:
    return max(1, int(_flags.env_flag("PADDLE_TPU_COLL_TIMEOUT_MS")))


def coll_epoch() -> str:
    """The collective-exchange epoch baked into every KV key. A
    restarted attempt runs under a NEW epoch (launch.py exports the
    restart count), so a respawned rank can never pair against its dead
    predecessor's stale payloads still sitting in the coordination
    service — the stale keys are dead by construction, no sweep RPC
    needed."""
    ep = str(_flags.env_flag("PADDLE_TPU_COLL_EPOCH")).strip()
    if ep:
        return ep
    return os.environ.get("PADDLE_RESTART_COUNT", "0") or "0"


def _unavailable(msg: str, *, missing_rank: Optional[int] = None,
                 tag: Optional[str] = None, reason: str = "timeout"):
    """Build the typed failure every detection path raises: an
    errors.Unavailable carrying the missing rank and collective tag as
    attributes, counted and flight-recorded."""
    from ..framework import errors as _errors

    if _monitor.enabled():
        _M_COLL_UNAVAIL.labels(reason=reason).inc()
    _monitor.flight_record("failure", "collective_unavailable",
                           reason=reason, missing_rank=missing_rank,
                           tag=tag, epoch=coll_epoch())
    e = _errors.errors.Unavailable(msg)
    e.missing_rank = missing_rank
    e.tag = tag
    e.reason = reason
    return e


def _is_deadline_error(e: Exception) -> bool:
    s = str(e)
    return ("DEADLINE_EXCEEDED" in s or "deadline" in s.lower()
            or "timed out" in s.lower() or isinstance(e, TimeoutError))


def _is_connection_error(e: Exception) -> bool:
    """The coordination service itself died under us (its host rank
    exited after detecting the failure first): connection-level errors
    on the KV channel are failure EVIDENCE, not infrastructure noise —
    they must surface typed like a timeout, never as a raw RPC error."""
    s = str(e)
    return any(m in s for m in (
        "Connection reset", "Broken pipe", "Socket closed",
        "failed to connect", "Connection refused", "UNAVAILABLE",
        "CANCELLED", "coordination service has shut down",
        "agent is in error status"))


def failure_key(epoch: Optional[str] = None) -> str:
    return f"paddle_tpu/failure/e{epoch if epoch is not None else coll_epoch()}"


def publish_failure(reason: str, missing_rank: Optional[int] = None,
                    tag: Optional[str] = None) -> None:
    """Publish this epoch's failure record to the coordination KV: the
    rank that detects a dead peer writes it ONCE, and every survivor
    polling between wait slices aborts its own in-flight exchange with
    the same verdict — coordinated detection instead of N independent
    full-deadline hangs. Best-effort (first writer wins; a dead
    coordination service means everyone is already failing loudly)."""
    try:
        client = _coord_client()
        doc = json.dumps({
            "epoch": coll_epoch(), "reporter": jax.process_index(),
            "missing_rank": missing_rank, "tag": tag, "reason": reason,
            "time_unix": time.time()})
        client.key_value_set(failure_key(), doc)
    except Exception:
        pass


def check_failure(client=None) -> Optional[dict]:
    """This epoch's published failure record, or None. A 1ms bounded get
    doubles as a non-blocking probe (absence IS a deadline error)."""
    try:
        client = client or _coord_client()
        raw = client.blocking_key_value_get(failure_key(), 1)
    except Exception:
        return None
    try:
        return json.loads(raw)
    except (TypeError, ValueError):
        return {"reason": "unparseable", "raw": str(raw)[:200]}


def _coord_client():
    from jax._src import distributed as _jdist

    client = getattr(_jdist.global_state, "client", None)
    if client is None:
        raise RuntimeError(
            "jax distributed runtime not initialized (init_parallel_env)")
    return client


def _kv_wait_bytes(client, key: str, deadline: float, *,
                   missing_rank: int, tag: str) -> bytes:
    """Bounded wait for one peer's payload: blocks in _KV_POLL_MS
    slices, polling the failure epoch between them. Expiry raises typed
    Unavailable naming the missing rank and tag, AND publishes the
    failure so every other survivor aborts consistently — the
    never-a-silent-hang contract."""
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            publish_failure("kv_timeout", missing_rank=missing_rank,
                            tag=tag)
            raise _unavailable(
                f"collective {tag!r}: rank {missing_rank} never "
                f"published {key!r} within {_coll_timeout_ms()}ms — "
                f"peer presumed dead (epoch {coll_epoch()})",
                missing_rank=missing_rank, tag=tag, reason="timeout")
        slice_ms = max(1, int(min(_KV_POLL_MS, remaining * 1e3)))
        try:
            return client.blocking_key_value_get_bytes(key, slice_ms)
        except Exception as e:
            if _is_connection_error(e):
                raise _unavailable(
                    f"collective {tag!r}: coordination service lost "
                    f"while waiting for rank {missing_rank} — its host "
                    f"rank exited after detecting a failure "
                    f"({type(e).__name__}: {str(e)[:200]})",
                    missing_rank=missing_rank, tag=tag,
                    reason="coordination_lost") from e
            if not _is_deadline_error(e):
                raise
        fail = check_failure(client)
        if fail is not None:
            raise _unavailable(
                f"collective {tag!r} aborted: failure epoch "
                f"{coll_epoch()} published by rank "
                f"{fail.get('reporter')} (missing rank "
                f"{fail.get('missing_rank')}, {fail.get('reason')})",
                missing_rank=fail.get("missing_rank"), tag=tag,
                reason="failure_epoch")


def _kv_allgather(tree, tag: Optional[str] = None):
    """Allgather a pytree of host-sized arrays through the coordination
    KV store: each rank publishes its pickled leaves under an
    epoch-scoped key, reads every rank's with a bounded deadline
    (PADDLE_TPU_COLL_TIMEOUT_MS — a dead peer surfaces as typed
    Unavailable, never a silent hang), and deletes its own after a
    barrier. Without a `tag`, keys come from a process-local sequence
    counter, which stays aligned only while every rank issues its
    collectives in the same order from ONE thread (the SPMD assumption
    every collective runtime makes). Concurrent issuers — the DP comms
    thread overlapping the backward — MUST pass a content-derived `tag`
    (bucketer uid + step + bucket index) so pairing is by identity,
    immune to cross-rank scheduling differences in dispatch order."""
    import pickle

    client = _coord_client()
    rank, n = jax.process_index(), jax.process_count()
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    payload = pickle.dumps([np.asarray(l) for l in leaves],
                           protocol=pickle.HIGHEST_PROTOCOL)
    epoch = coll_epoch()
    base = (f"paddle_tpu/allgather/e{epoch}/t/{tag}" if tag
            else f"paddle_tpu/allgather/e{epoch}/{next(_AG_SEQ)}")
    client.key_value_set_bytes(f"{base}/{rank}", payload)
    deadline = time.monotonic() + _coll_timeout_ms() / 1e3
    gathered = [
        pickle.loads(_kv_wait_bytes(client, f"{base}/{r}", deadline,
                                    missing_rank=r, tag=tag or base))
        for r in range(n)
    ]
    barrier_ms = max(1, int((deadline - time.monotonic()) * 1e3))
    try:
        client.wait_at_barrier(f"{base}/done", barrier_ms)
    except Exception as e:
        if _is_connection_error(e):
            raise _unavailable(
                f"collective {tag or base!r}: coordination service lost "
                f"at the barrier ({type(e).__name__}: {str(e)[:200]})",
                tag=tag or base, reason="coordination_lost") from e
        if not _is_deadline_error(e):
            raise
        # every payload arrived but a peer died before the barrier
        publish_failure("barrier_timeout", tag=tag)
        raise _unavailable(
            f"collective {tag or base!r}: barrier never completed "
            f"within the deadline — a peer died after publishing "
            f"(epoch {epoch})", tag=tag or base,
            reason="barrier_timeout") from e
    client.key_value_delete(f"{base}/{rank}")
    stacked = [np.stack([g[i] for g in gathered])
               for i in range(len(leaves))]
    return jax.tree_util.tree_unflatten(treedef, stacked)


def _xla_collectives_unsupported(e: Exception) -> bool:
    return ("Multiprocess computations aren't implemented" in str(e)
            or isinstance(e, NotImplementedError))


def _process_allgather(x, tag: Optional[str] = None):
    """Gather `x` (array or pytree) from every process; returns each
    leaf stacked [nproc, ...]. A `tag` requests IDENTITY pairing and
    always routes through the coordination-KV exchange: the XLA
    process_allgather pairs strictly by cross-rank launch order, which
    concurrent issuers (the DP comms thread overlapping the backward)
    cannot guarantee — two threads winning the dispatch race in
    different orders on different ranks would pair mismatched payloads.
    Untagged calls (single-threaded API collectives) keep the XLA-first
    path with the KV fallback for backends that reject multiprocess
    programs."""
    global _KV_FALLBACK
    if tag is not None:
        return _kv_allgather(x, tag=tag)
    if not _KV_FALLBACK:
        from jax.experimental import multihost_utils

        try:
            return multihost_utils.process_allgather(x)
        except Exception as e:
            if not _xla_collectives_unsupported(e):
                raise
            _KV_FALLBACK = True
    return _kv_allgather(x)


def _all_reduce_impl(tensor, op):
    if _nproc() == 1:
        return tensor
    stacked = _process_allgather(_eager_value(tensor))
    if op == ReduceOp.SUM:
        out = stacked.sum(axis=0)
    elif op == ReduceOp.MAX:
        out = stacked.max(axis=0)
    elif op == ReduceOp.MIN:
        out = stacked.min(axis=0)
    else:
        out = jnp.prod(stacked, axis=0)
    return _wrap_like(tensor, jnp.asarray(out))


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In-place all-reduce across trainer processes (reference
    collective.py:59)."""
    with _collective_window("all_reduce", tensor):
        return _all_reduce_impl(tensor, op)


def all_gather(tensor_list: List, tensor, group=None, sync_op=True):
    """Gather tensors from all trainers into tensor_list (reference
    collective.py:226)."""
    from ..dygraph.varbase import Tensor

    with _collective_window("all_gather", tensor):
        if _nproc() == 1:
            tensor_list.append(_wrap_like(None, _eager_value(tensor)))
            return tensor_list
        stacked = _process_allgather(_eager_value(tensor))
        for i in range(stacked.shape[0]):
            tensor_list.append(Tensor(jnp.asarray(stacked[i])))
        return tensor_list


def broadcast(tensor, src: int = 0, group=None, sync_op=True):
    """Broadcast from rank `src` (reference collective.py:140)."""
    with _collective_window("broadcast", tensor):
        if _nproc() == 1:
            return tensor
        stacked = _process_allgather(_eager_value(tensor))
        return _wrap_like(tensor, jnp.asarray(stacked[src]))


def reduce(tensor, dst: int = 0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reduce to rank `dst`; other ranks keep their value (reference
    collective.py:182)."""
    with _collective_window("reduce", tensor):
        return _all_reduce_impl(tensor, op)


def scatter(tensor, tensor_list=None, src: int = 0, group=None, sync_op=True):
    """Scatter list from src (reference collective.py:300)."""
    with _collective_window("scatter", tensor):
        if _nproc() == 1:
            if tensor_list:
                return _wrap_like(tensor, _eager_value(tensor_list[0]))
            return tensor
        # src's list is materialized on every process via gather-of-lists
        rank = jax.process_index()
        vals = [_eager_value(t) for t in (tensor_list or [tensor])]
        stacked = _process_allgather(jnp.stack(vals))  # [nproc, n, ...]
        return _wrap_like(tensor, jnp.asarray(stacked[src][rank]))


def barrier(group=None):
    """Reference collective.py:419 / barrier_op; sync over the JAX
    distributed runtime."""
    with _collective_window("barrier"):
        if _nproc() == 1:
            return
        global _KV_FALLBACK
        if not _KV_FALLBACK:
            from jax.experimental import multihost_utils

            try:
                multihost_utils.sync_global_devices(
                    "paddle_tpu.distributed.barrier")
                return
            except Exception as e:
                if not _xla_collectives_unsupported(e):
                    raise
                _KV_FALLBACK = True
        # an allgather IS a barrier: every rank blocks for every other
        _kv_allgather(np.asarray([jax.process_index()], np.int32))


def split(*args, **kwargs):  # model-parallel fc/embedding split helper
    raise NotImplementedError(
        "paddle.distributed.split: use mesh sharding rules "
        "(paddle_tpu.parallel.shard_scope) for model parallelism"
    )
