"""paddle.distributed collective API.

Counterpart of /root/reference/python/paddle/distributed/collective.py:59-419
(all_reduce/all_gather/broadcast/reduce/scatter/barrier built on c_* NCCL
ops). Two TPU-native execution paths replace the NCCL rings:

1. **In-program (static / jit)**: placement-first. Sharded parameters and
   batches let XLA/GSPMD derive the collectives; the c_* ops lower to
   `lax.p*` only when traced inside `shard_map` (manual-SPMD regions, e.g.
   sync_batch_norm), and to identity under plain GSPMD jit, where the
   equivalent reduction is already implied by shardings (SURVEY.md §5.8).
2. **Eager (dygraph)**: cross-process collectives over the JAX distributed
   runtime (one process per TPU host), via the global-array trick:
   all-reduce = all_gather over processes + local reduction. With one
   process they are identities, matching reference world_size==1 behavior.
"""
from __future__ import annotations

import contextlib
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import goodput as _goodput
from .. import monitor as _monitor
from .. import profiler as _profiler

# per-collective call counts and payload bytes (the local tensor's size —
# what this rank contributes to the wire, world-size independent)
_M_COLL = _monitor.counter(
    "collective_calls_total", "collective API invocations", ("op",))
_M_COLL_B = _monitor.counter(
    "collective_bytes_total", "local payload bytes per collective", ("op",))


@contextlib.contextmanager
def _collective_window(op_name: str, value=None):
    """Count + span + goodput attribution around one collective: the
    host-blocking wall time of the call is the per-collective time
    budget (EQuARX-style accounting) and the 'collective' badput bucket
    of the step it stalls."""
    _record_collective(op_name, value)
    t0 = time.perf_counter()
    with _profiler.span(f"collective/{op_name}", cat="collective"):
        try:
            yield
        finally:
            _goodput.add("collective", time.perf_counter() - t0)


def _record_collective(op_name: str, value=None) -> None:
    if not _monitor.enabled():
        return
    _M_COLL.labels(op=op_name).inc()
    if value is not None:
        # size from metadata, never a device conversion: dygraph Tensors
        # expose their jax array via _value, arrays expose nbytes
        v = getattr(value, "_value", value)
        nbytes = getattr(v, "nbytes", None)
        if nbytes is None:
            nbytes = int(np.asarray(v).nbytes)
        _M_COLL_B.labels(op=op_name).inc(float(nbytes))


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3


def _nproc() -> int:
    return jax.process_count()


def _eager_value(t):
    from ..dygraph.varbase import Tensor

    if isinstance(t, Tensor):
        return t._value
    return jnp.asarray(t)


def _wrap_like(t, val):
    from ..dygraph.varbase import Tensor

    if isinstance(t, Tensor):
        t._value = val
        return t
    return Tensor(val)


def _process_allgather(x):
    """Gather `x` from every process; returns stacked [nproc, ...]."""
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(x)


def _all_reduce_impl(tensor, op):
    if _nproc() == 1:
        return tensor
    stacked = _process_allgather(_eager_value(tensor))
    if op == ReduceOp.SUM:
        out = stacked.sum(axis=0)
    elif op == ReduceOp.MAX:
        out = stacked.max(axis=0)
    elif op == ReduceOp.MIN:
        out = stacked.min(axis=0)
    else:
        out = jnp.prod(stacked, axis=0)
    return _wrap_like(tensor, jnp.asarray(out))


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In-place all-reduce across trainer processes (reference
    collective.py:59)."""
    with _collective_window("all_reduce", tensor):
        return _all_reduce_impl(tensor, op)


def all_gather(tensor_list: List, tensor, group=None, sync_op=True):
    """Gather tensors from all trainers into tensor_list (reference
    collective.py:226)."""
    from ..dygraph.varbase import Tensor

    with _collective_window("all_gather", tensor):
        if _nproc() == 1:
            tensor_list.append(_wrap_like(None, _eager_value(tensor)))
            return tensor_list
        stacked = _process_allgather(_eager_value(tensor))
        for i in range(stacked.shape[0]):
            tensor_list.append(Tensor(jnp.asarray(stacked[i])))
        return tensor_list


def broadcast(tensor, src: int = 0, group=None, sync_op=True):
    """Broadcast from rank `src` (reference collective.py:140)."""
    with _collective_window("broadcast", tensor):
        if _nproc() == 1:
            return tensor
        stacked = _process_allgather(_eager_value(tensor))
        return _wrap_like(tensor, jnp.asarray(stacked[src]))


def reduce(tensor, dst: int = 0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reduce to rank `dst`; other ranks keep their value (reference
    collective.py:182)."""
    with _collective_window("reduce", tensor):
        return _all_reduce_impl(tensor, op)


def scatter(tensor, tensor_list=None, src: int = 0, group=None, sync_op=True):
    """Scatter list from src (reference collective.py:300)."""
    with _collective_window("scatter", tensor):
        if _nproc() == 1:
            if tensor_list:
                return _wrap_like(tensor, _eager_value(tensor_list[0]))
            return tensor
        # src's list is materialized on every process via gather-of-lists
        rank = jax.process_index()
        vals = [_eager_value(t) for t in (tensor_list or [tensor])]
        stacked = _process_allgather(jnp.stack(vals))  # [nproc, n, ...]
        return _wrap_like(tensor, jnp.asarray(stacked[src][rank]))


def barrier(group=None):
    """Reference collective.py:419 / barrier_op; sync over the JAX
    distributed runtime."""
    with _collective_window("barrier"):
        if _nproc() == 1:
            return
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("paddle_tpu.distributed.barrier")


def split(*args, **kwargs):  # model-parallel fc/embedding split helper
    raise NotImplementedError(
        "paddle.distributed.split: use mesh sharding rules "
        "(paddle_tpu.parallel.shard_scope) for model parallelism"
    )
