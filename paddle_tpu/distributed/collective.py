"""paddle.distributed collective API.

Counterpart of /root/reference/python/paddle/distributed/collective.py:59-419
(all_reduce/all_gather/broadcast/reduce/scatter/barrier built on c_* NCCL
ops). Two TPU-native execution paths replace the NCCL rings:

1. **In-program (static / jit)**: placement-first. Sharded parameters and
   batches let XLA/GSPMD derive the collectives; the c_* ops lower to
   `lax.p*` only when traced inside `shard_map` (manual-SPMD regions, e.g.
   sync_batch_norm), and to identity under plain GSPMD jit, where the
   equivalent reduction is already implied by shardings (SURVEY.md §5.8).
2. **Eager (dygraph)**: cross-process collectives over the JAX distributed
   runtime (one process per TPU host), via the global-array trick:
   all-reduce = all_gather over processes + local reduction. With one
   process they are identities, matching reference world_size==1 behavior.
"""
from __future__ import annotations

import contextlib
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import goodput as _goodput
from .. import monitor as _monitor
from .. import profiler as _profiler

# per-collective call counts and WIRE bytes — what this rank actually
# contributes to the network per call (the quantized payload + scales in
# int8 mode, the packed fp32 buffer for exact buckets), world-size
# independent. collective_logical_bytes_total carries the fp32-equivalent
# size of the same payloads, so quantized-vs-exact compression is
# auditable from any metrics snapshot (obs_report's comms section).
_M_COLL = _monitor.counter(
    "collective_calls_total", "collective API invocations", ("op",))
_M_COLL_B = _monitor.counter(
    "collective_bytes_total",
    "local WIRE payload bytes per collective (post-quantization)", ("op",))
_M_COLL_LB = _monitor.counter(
    "collective_logical_bytes_total",
    "logical (fp32-equivalent) payload bytes per collective", ("op",))


@contextlib.contextmanager
def _collective_window(op_name: str, value=None):
    """Count + span + goodput attribution around one collective: the
    host-blocking wall time of the call is the per-collective time
    budget (EQuARX-style accounting) and the 'collective' badput bucket
    of the step it stalls."""
    _record_collective(op_name, value)
    t0 = time.perf_counter()
    with _profiler.span(f"collective/{op_name}", cat="collective"):
        try:
            yield
        finally:
            _goodput.add("collective", time.perf_counter() - t0)


def _value_nbytes(value) -> int:
    # size from metadata, never a device conversion: dygraph Tensors
    # expose their jax array via _value, arrays expose nbytes
    v = getattr(value, "_value", value)
    nbytes = getattr(v, "nbytes", None)
    if nbytes is None:
        nbytes = int(np.asarray(v).nbytes)
    return int(nbytes)


def _record_collective(op_name: str, value=None,
                       nbytes: Optional[int] = None,
                       logical_nbytes: Optional[int] = None) -> None:
    """Count one collective. For plain API calls the tensor IS the wire
    payload (``value``); the bucketed/quantized paths pass the true wire
    byte count explicitly (``nbytes``) plus the fp32-equivalent
    (``logical_nbytes``) so the byte series never reports a logical fp32
    tensor the wire never carried."""
    if not _monitor.enabled():
        return
    _M_COLL.labels(op=op_name).inc()
    if nbytes is None and value is not None:
        nbytes = _value_nbytes(value)
    if nbytes is not None:
        _M_COLL_B.labels(op=op_name).inc(float(nbytes))
        _M_COLL_LB.labels(op=op_name).inc(
            float(logical_nbytes if logical_nbytes is not None else nbytes))


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3


def _nproc() -> int:
    return jax.process_count()


def _eager_value(t):
    from ..dygraph.varbase import Tensor

    if isinstance(t, Tensor):
        return t._value
    return jnp.asarray(t)


def _wrap_like(t, val):
    from ..dygraph.varbase import Tensor

    if isinstance(t, Tensor):
        t._value = val
        return t
    return Tensor(val)


# host-side allgather fallback over the jax coordination-service KV
# store: some backends (the CPU simulator this repo tests multi-process
# on) reject multiprocess XLA computations outright, which kills
# multihost_utils.process_allgather at compile time. The rendezvous
# service itself still works, so eager collectives fall back to moving
# the (host-sized) payloads through it. The failed attempt is
# compile-local — every rank fails identically before any cross-rank
# exchange — so flipping to the fallback is rank-consistent.
_KV_FALLBACK = False
_KV_TIMEOUT_MS = 300_000
_AG_SEQ = iter(range(1 << 62))


def _coord_client():
    from jax._src import distributed as _jdist

    client = getattr(_jdist.global_state, "client", None)
    if client is None:
        raise RuntimeError(
            "jax distributed runtime not initialized (init_parallel_env)")
    return client


def _kv_allgather(tree, tag: Optional[str] = None):
    """Allgather a pytree of host-sized arrays through the coordination
    KV store: each rank publishes its pickled leaves under a key, reads
    every rank's, and deletes its own after a barrier. Without a `tag`,
    keys come from a process-local sequence counter, which stays aligned
    only while every rank issues its collectives in the same order from
    ONE thread (the SPMD assumption every collective runtime makes).
    Concurrent issuers — the DP comms thread overlapping the backward —
    MUST pass a content-derived `tag` (bucketer uid + step + bucket
    index) so pairing is by identity, immune to cross-rank scheduling
    differences in dispatch order."""
    import pickle

    client = _coord_client()
    rank, n = jax.process_index(), jax.process_count()
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    payload = pickle.dumps([np.asarray(l) for l in leaves],
                           protocol=pickle.HIGHEST_PROTOCOL)
    base = (f"paddle_tpu/allgather/t/{tag}" if tag
            else f"paddle_tpu/allgather/{next(_AG_SEQ)}")
    client.key_value_set_bytes(f"{base}/{rank}", payload)
    gathered = [
        pickle.loads(client.blocking_key_value_get_bytes(
            f"{base}/{r}", _KV_TIMEOUT_MS))
        for r in range(n)
    ]
    client.wait_at_barrier(f"{base}/done", _KV_TIMEOUT_MS)
    client.key_value_delete(f"{base}/{rank}")
    stacked = [np.stack([g[i] for g in gathered])
               for i in range(len(leaves))]
    return jax.tree_util.tree_unflatten(treedef, stacked)


def _xla_collectives_unsupported(e: Exception) -> bool:
    return ("Multiprocess computations aren't implemented" in str(e)
            or isinstance(e, NotImplementedError))


def _process_allgather(x, tag: Optional[str] = None):
    """Gather `x` (array or pytree) from every process; returns each
    leaf stacked [nproc, ...]. A `tag` requests IDENTITY pairing and
    always routes through the coordination-KV exchange: the XLA
    process_allgather pairs strictly by cross-rank launch order, which
    concurrent issuers (the DP comms thread overlapping the backward)
    cannot guarantee — two threads winning the dispatch race in
    different orders on different ranks would pair mismatched payloads.
    Untagged calls (single-threaded API collectives) keep the XLA-first
    path with the KV fallback for backends that reject multiprocess
    programs."""
    global _KV_FALLBACK
    if tag is not None:
        return _kv_allgather(x, tag=tag)
    if not _KV_FALLBACK:
        from jax.experimental import multihost_utils

        try:
            return multihost_utils.process_allgather(x)
        except Exception as e:
            if not _xla_collectives_unsupported(e):
                raise
            _KV_FALLBACK = True
    return _kv_allgather(x)


def _all_reduce_impl(tensor, op):
    if _nproc() == 1:
        return tensor
    stacked = _process_allgather(_eager_value(tensor))
    if op == ReduceOp.SUM:
        out = stacked.sum(axis=0)
    elif op == ReduceOp.MAX:
        out = stacked.max(axis=0)
    elif op == ReduceOp.MIN:
        out = stacked.min(axis=0)
    else:
        out = jnp.prod(stacked, axis=0)
    return _wrap_like(tensor, jnp.asarray(out))


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In-place all-reduce across trainer processes (reference
    collective.py:59)."""
    with _collective_window("all_reduce", tensor):
        return _all_reduce_impl(tensor, op)


def all_gather(tensor_list: List, tensor, group=None, sync_op=True):
    """Gather tensors from all trainers into tensor_list (reference
    collective.py:226)."""
    from ..dygraph.varbase import Tensor

    with _collective_window("all_gather", tensor):
        if _nproc() == 1:
            tensor_list.append(_wrap_like(None, _eager_value(tensor)))
            return tensor_list
        stacked = _process_allgather(_eager_value(tensor))
        for i in range(stacked.shape[0]):
            tensor_list.append(Tensor(jnp.asarray(stacked[i])))
        return tensor_list


def broadcast(tensor, src: int = 0, group=None, sync_op=True):
    """Broadcast from rank `src` (reference collective.py:140)."""
    with _collective_window("broadcast", tensor):
        if _nproc() == 1:
            return tensor
        stacked = _process_allgather(_eager_value(tensor))
        return _wrap_like(tensor, jnp.asarray(stacked[src]))


def reduce(tensor, dst: int = 0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reduce to rank `dst`; other ranks keep their value (reference
    collective.py:182)."""
    with _collective_window("reduce", tensor):
        return _all_reduce_impl(tensor, op)


def scatter(tensor, tensor_list=None, src: int = 0, group=None, sync_op=True):
    """Scatter list from src (reference collective.py:300)."""
    with _collective_window("scatter", tensor):
        if _nproc() == 1:
            if tensor_list:
                return _wrap_like(tensor, _eager_value(tensor_list[0]))
            return tensor
        # src's list is materialized on every process via gather-of-lists
        rank = jax.process_index()
        vals = [_eager_value(t) for t in (tensor_list or [tensor])]
        stacked = _process_allgather(jnp.stack(vals))  # [nproc, n, ...]
        return _wrap_like(tensor, jnp.asarray(stacked[src][rank]))


def barrier(group=None):
    """Reference collective.py:419 / barrier_op; sync over the JAX
    distributed runtime."""
    with _collective_window("barrier"):
        if _nproc() == 1:
            return
        global _KV_FALLBACK
        if not _KV_FALLBACK:
            from jax.experimental import multihost_utils

            try:
                multihost_utils.sync_global_devices(
                    "paddle_tpu.distributed.barrier")
                return
            except Exception as e:
                if not _xla_collectives_unsupported(e):
                    raise
                _KV_FALLBACK = True
        # an allgather IS a barrier: every rank blocks for every other
        _kv_allgather(np.asarray([jax.process_index()], np.int32))


def split(*args, **kwargs):  # model-parallel fc/embedding split helper
    raise NotImplementedError(
        "paddle.distributed.split: use mesh sharding rules "
        "(paddle_tpu.parallel.shard_scope) for model parallelism"
    )
