"""Dygraph data parallelism.

Counterpart of /root/reference/python/paddle/fluid/dygraph/parallel.py:236
(DataParallel: scale_loss :337 + apply_collective_grads :449 coalescing
grads then NCCL all-reduce) and paddle.distributed.parallel.init_parallel_env
(parallel.py:32, NCCL-id TCP rendezvous imperative/nccl_context.h:61).
TPU-native: rendezvous is jax.distributed (coordination service), the grad
all-reduce is a process-level collective, and single-host multi-chip runs
use mesh sharding instead (the chips of one host belong to one process).
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from ..nn.layers import Layer
from ..parallel.env import ParallelEnv, get_rank, get_world_size, init_parallel_env
from . import collective


class DataParallel(Layer):
    """Wraps a Layer; averages gradients across trainer processes after
    backward. Usage parity with reference parallel.py:236:

        model = paddle.DataParallel(model)
        loss = model(x); loss.backward()
        model.apply_collective_grads()   # or rely on optimizer hook
        opt.step()
    """

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size_mb: int = 25):
        super().__init__()
        self._layers = layers
        self._nranks = get_world_size()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        """Reference parallel.py:337 — average loss contribution. The grad
        all-reduce sums across ranks, so pre-scale by 1/nranks."""
        if self._nranks <= 1:
            return loss
        return loss / float(self._nranks)

    def apply_collective_grads(self):
        """Reference parallel.py:449 — coalesce + all-reduce every grad.
        Coalescing is unnecessary here (one fused XLA program per gather),
        so each grad is reduced directly."""
        if self._nranks <= 1:
            return
        for p in self._layers.parameters():
            if p.grad is not None:
                collective.all_reduce(p.grad)

    # passthroughs
    def parameters(self, include_sublayers=True):
        return self._layers.parameters()

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)
