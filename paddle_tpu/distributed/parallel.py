"""Dygraph data parallelism.

Counterpart of /root/reference/python/paddle/fluid/dygraph/parallel.py:236
(DataParallel: scale_loss :337 + apply_collective_grads :449 coalescing
grads then NCCL all-reduce) and paddle.distributed.parallel.init_parallel_env
(parallel.py:32, NCCL-id TCP rendezvous imperative/nccl_context.h:61).
TPU-native: rendezvous is jax.distributed (coordination service), and the
grad sync is the bucketed, backward-overlapped (optionally int8-quantized)
comms layer in distributed/comms.py — the reference's coalescing idea, but
dispatched per-bucket as gradients become ready instead of one blocking
NCCL call per parameter after backward. Single-host multi-chip runs use
mesh sharding instead (the chips of one host belong to one process), so
with one process the whole layer is inert.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from ..nn.layers import Layer
from ..parallel.env import ParallelEnv, get_rank, get_world_size, init_parallel_env
from . import collective


class DataParallel(Layer):
    """Wraps a Layer; averages gradients across trainer processes after
    backward. Usage parity with reference parallel.py:236:

        model = paddle.DataParallel(model)
        loss = model(x); loss.backward()
        model.apply_collective_grads()   # or rely on optimizer hook
        opt.step()

    Comm behavior (nranks > 1) is driven by the PADDLE_TPU_DP_* env knobs
    (or the ``comm_buffer_size_mb`` argument, reference-compatible):
    grads coalesce into ~``bucket_mb`` byte buckets which dispatch as
    soon as the backward produces their last gradient (tracer grad-ready
    hook), overlapping the remaining backward; ``PADDLE_TPU_DP_QUANTIZE=
    int8`` ships blockwise-int8 payloads with error feedback. Setting
    ``PADDLE_TPU_DP_BUCKET_MB=0`` (or ``comm_buffer_size_mb=0``) restores
    the legacy one-blocking-all-reduce-per-parameter loop.
    """

    def __init__(self, layers: Layer, strategy=None,
                 comm_buffer_size_mb: Optional[float] = None):
        super().__init__()
        self._layers = layers
        self._nranks = get_world_size()
        self._comms = None
        self._grad_hook = None
        if self._nranks > 1:
            from . import comms

            mb = (comms.bucket_mb() if comm_buffer_size_mb is None
                  else float(comm_buffer_size_mb))
            if mb > 0:
                self._comms = comms.GradBucketer(
                    self._layers.parameters(), bucket_mb=mb)
                self._register_grad_hook()

    def _register_grad_hook(self) -> None:
        """Wire the bucketer into the tracer's grad-ready stream so
        buckets dispatch DURING backward. Without an active tracer
        (static mode) the sync-time sweep in apply_collective_grads
        still buckets everything — only the overlap is lost.

        The hook holds only a WEAK reference to the bucketer and
        unregisters itself once the wrapper is garbage-collected: a
        discarded DataParallel (retry loops, notebooks) must not keep
        firing collectives from beyond the grave — a zombie bucketer
        racing a live one would interleave exchanges and leak its
        model-sized residual buffers for the process lifetime."""
        import weakref

        from ..dygraph import base as dybase

        tracer = dybase._active_tracer()
        if tracer is None or self._comms is None:
            return
        ref = weakref.ref(self._comms)

        def _on_grad_ready(name, value, _ref=ref, _tracer=tracer):
            b = _ref()
            if b is None:
                _tracer.remove_grad_ready_hook(_on_grad_ready)
                return
            b.grad_ready(name, value)

        self._grad_hook = _on_grad_ready
        tracer.register_grad_ready_hook(self._grad_hook)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        """Reference parallel.py:337 — average loss contribution. The grad
        all-reduce sums across ranks, so pre-scale by 1/nranks."""
        if self._nranks <= 1:
            return loss
        return loss / float(self._nranks)

    def apply_collective_grads(self):
        """Reference parallel.py:449 — the sync point before the
        optimizer consumes the grads. Bucketed path: sweep any bucket
        the backward hooks did not fire (stragglers, hook-less custom
        loops), block for the in-flight collectives, and install the
        reduced values. Falls back to the exact per-parameter all-reduce
        for any gradient the bucketer did not carry this step (grad
        accumulated across backwards, or bucketing disabled)."""
        if self._nranks <= 1:
            return
        params = self._layers.parameters()
        reduced = {}
        staged = {}
        stale_buckets = set()
        if self._comms is not None:
            staged = {p.name: self._comms.staged_value(p.name)
                      for p in params}
            reduced = self._comms.sync()
            # payload validity is decided per BUCKET: if any parameter's
            # grad changed under the in-flight dispatch (a second
            # backward accumulated into it), the whole bucket's payload
            # is stale — applying the other slices while rolling back
            # the bucket's shared residual would double-compensate them
            for p in params:
                if (p.grad is not None and reduced.get(p.name) is not None
                        and staged.get(p.name) is not p.grad._value):
                    stale_buckets.add(self._comms.bucket_index(p.name))
        for p in params:
            if p.grad is None:
                continue
            r = reduced.get(p.name)
            fresh = (r is not None
                     and (self._comms is None
                          or self._comms.bucket_index(p.name)
                          not in stale_buckets))
            if fresh:
                # the bucketer shipped exactly this backward's gradients
                p.grad._value = jnp.asarray(r, p.grad._value.dtype)
            else:
                # stale bucket or never staged (bucketing off /
                # accumulation under the dispatch): exact, correct, slow
                if r is not None and self._comms is not None:
                    # the discarded payload's error-feedback residual
                    # update must not stand (idempotent per bucket)
                    self._comms.rollback_residual_for(p.name)
                collective.all_reduce(p.grad)

    # passthroughs
    def parameters(self, include_sublayers=True):
        return self._layers.parameters()

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)
