"""Cluster launcher: `python -m paddle_tpu.distributed.launch train.py`.

Counterpart of /root/reference/python/paddle/distributed/launch.py:214 and
fleet/launch_utils.py:409-440 — builds the cluster map and spawns one
worker process per *host* (not per chip: on TPU all local chips belong to
one process; SURVEY.md §7.2.6) with the same PADDLE_* env protocol:
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_CURRENT_ENDPOINT /
PADDLE_TRAINER_ENDPOINTS. Workers rendezvous via jax.distributed
(paddle_tpu.parallel.env.init_parallel_env) instead of NCCL-id broadcast.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List


def _parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument(
        "--ips", type=str, default="127.0.0.1",
        help="comma-separated host ips of the job (reference --cluster_node_ips)",
    )
    p.add_argument(
        "--nproc_per_node", type=int, default=1,
        help="worker processes per host; >1 only for CPU-simulation runs "
        "(one process per TPU host owns all its chips)",
    )
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument(
        "--trace_dir", type=str,
        default=os.environ.get("PADDLE_TPU_TRACE_DIR"),
        help="enable distributed tracing: every rank records spans and "
        "writes trace.rank<k>.json here (merge with tools/timeline.py); "
        "flight-recorder dumps from dead/hung ranks land here too",
    )
    p.add_argument(
        "--status_port", type=int,
        default=int(os.environ.get("PADDLE_TPU_STATUS_PORT", "0") or 0),
        help="serve a live status endpoint per rank: rank k binds "
        "status_port+k and answers /status, /metrics and /healthz "
        "(paddle_tpu.status); 0 disables",
    )
    p.add_argument(
        "--goodput_dir", type=str,
        default=os.environ.get("PADDLE_TPU_GOODPUT_DIR"),
        help="persist each rank's goodput ledger journal "
        "(goodput.rank<k>.json) here; the launcher prints the merged "
        "job-level goodput summary at teardown (defaults to --trace_dir "
        "when that is set)",
    )
    p.add_argument(
        "--dp_bucket_mb", type=float, default=None,
        help="gradient-sync bucket size (MB) exported to every rank as "
        "PADDLE_TPU_DP_BUCKET_MB; 0 restores the per-parameter "
        "all-reduce loop (unset: the ranks' env/default decides)",
    )
    p.add_argument(
        "--dp_quantize", type=str, default=None, choices=("none", "int8"),
        help="gradient all-reduce wire encoding exported as "
        "PADDLE_TPU_DP_QUANTIZE: int8 = blockwise-quantized with error "
        "feedback (~4x fewer wire bytes), none = exact fp32",
    )
    p.add_argument(
        "--dp_overlap", type=str, default=None, choices=("0", "1"),
        help="PADDLE_TPU_DP_OVERLAP for the ranks: 1 dispatches grad "
        "buckets during the backward (default), 0 defers them to the "
        "sync point (debugging aid)",
    )
    p.add_argument(
        "--serve", action="store_true",
        help="serving-replica mode: each spawned worker is a serving "
        "replica — PADDLE_TPU_SERVE_DIR is exported so every replica "
        "journals its serving ledger (serving.rank<k>.json; defaults "
        "to --serve_dir, then --goodput_dir/--trace_dir), and the "
        "supervisor prints the merged SLO summary (tokens/s, TTFT/p99, "
        "occupancy, serving goodput buckets) at teardown; with "
        "--elastic_retries > 0 a dead replica respawns IN PLACE (warm "
        "restart) regardless of --elastic_mode — replicas have no "
        "collective membership to restart together",
    )
    p.add_argument(
        "--serve_dir", type=str,
        default=os.environ.get("PADDLE_TPU_SERVE_DIR"),
        help="directory for the per-replica serving journals "
        "(PADDLE_TPU_SERVE_DIR exported to children under --serve)",
    )
    p.add_argument(
        "--ckpt_dir", type=str,
        default=os.environ.get("PADDLE_TPU_CKPT_DIR"),
        help="export PADDLE_TPU_CKPT_DIR to every rank: the hapi fit "
        "loop writes periodic atomic full-state training checkpoints "
        "(params + optimizer incl. EF residuals + step + data cursor) "
        "there and a respawned rank auto-resumes from the newest one — "
        "the recovery half of --elastic_retries",
    )
    p.add_argument(
        "--elastic_retries", type=int, default=0,
        help="restart the whole local worker set up to N times after a "
        "failure (job-level elasticity; workers resume from their "
        "auto-checkpoints — incubate.checkpoint.auto_checkpoint)",
    )
    p.add_argument(
        "--elastic_mode", type=str, default="restart_all",
        choices=("restart_all", "respawn_worker"),
        help="restart_all: any failure tears down and relaunches every "
        "local worker (collective mode needs consistent membership); "
        "respawn_worker: only the failed rank restarts in place (PS "
        "mode, where trainers are independent) — single-worker rejoin",
    )
    p.add_argument(
        "--heartbeat_endpoints", type=str, default="",
        help="comma-separated pserver endpoints to poll for trainer "
        "liveness; a LOCAL rank the servers consider dead while its "
        "process still runs (hung trainer) is killed and respawned",
    )
    p.add_argument(
        "--heartbeat_timeout", type=float, default=30.0,
        help="seconds without a beat before a trainer counts as dead",
    )
    p.add_argument("--host_rank", type=int, default=int(os.environ.get("POD_INDEX", "0")))
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def get_cluster_endpoints(ips: List[str], nproc: int, port: int) -> List[str]:
    eps = []
    for ip in ips:
        for i in range(nproc):
            eps.append(f"{ip}:{port + i}")
    return eps


def _shed_rank_observability() -> None:
    """The launcher imports paddle_tpu itself, so with the
    rank-observability env exported (PADDLE_TPU_STATUS_PORT /
    PADDLE_TPU_GOODPUT_DIR) the import wiring gave THIS process a rank
    identity it must not keep: release the status port (or rank 0's
    bind at base+0 fails) and drop journal persistence (or the
    launcher's exit flush clobbers rank 0's journal)."""
    try:
        from .. import commswatch, dynamics, goodput, memwatch, status
        from ..serving import ledger as serving_ledger

        status.stop_status_server()
        goodput.disable_persistence()
        memwatch.disable_persistence()
        dynamics.disable_persistence()
        commswatch.disable_persistence()
        # the serving env shares the shedding idiom: a supervisor that
        # inherited PADDLE_TPU_SERVE_DIR must not clobber replica 0's
        # serving journal with its own (empty) exit flush
        serving_ledger.disable_persistence()
    except Exception:
        pass  # observability shedding must never block the launch


def launch(args) -> int:
    """Spawn + supervise the local workers; with --elastic_retries, a
    failed worker set is torn down and restarted (the reference
    launch_utils.py:409-440 watch loop is fail-fast only; restart is the
    elastic extension, with auto-checkpoint providing resume)."""
    _shed_rank_observability()
    attempts = 0
    while True:
        rc = _launch_once(args, attempts)
        if rc == 0 or attempts >= args.elastic_retries:
            return rc
        attempts += 1
        time.sleep(1.0)


def _clear_heartbeat(endpoints: List[str], trainer_id: int) -> None:
    """Reset the pservers' stale timestamp for a killed+respawned rank so
    the fresh worker is not re-flagged before its first beat."""
    from .ps.rpc import PSClient

    for ep in endpoints:
        try:
            client = PSClient(ep, timeout=5.0, recv_timeout=5.0)
            client.call("heartbeat_clear", trainer_id=trainer_id)
            client.close()
        except Exception:
            continue


def _collect_flight_dumps(trace_dir: str, seen: set) -> List[str]:
    """Surface flight-recorder dumps (monitor.dump_flight_record files)
    that appeared since the last sweep — the launcher's 'what was the
    dead rank doing' report, printed as it reaps workers."""
    import glob
    import json as _json

    found = []
    for path in sorted(glob.glob(os.path.join(trace_dir, "flight.*.json"))):
        if path in seen:
            continue
        seen.add(path)
        line = f"[launch] flight-recorder dump: {path}"
        try:
            with open(path) as f:
                doc = _json.load(f)
            line = (f"[launch] flight-recorder dump from rank "
                    f"{doc.get('rank')} ({doc.get('reason') or 'unknown'}, "
                    f"{len(doc.get('events', []))} events, "
                    f"{len(doc.get('stacks', {}))} threads): {path}")
        except (OSError, ValueError):
            pass  # half-written dump: still name the file
        print(line, file=sys.stderr)
        found.append(path)
    return found


def _request_flight_dump(proc, wait: float = 1.0) -> None:
    """Ask a live-but-suspect worker to dump its flight record (SIGUSR1,
    handled by monitor.install_dump_handlers) before it is killed."""
    if not hasattr(signal, "SIGUSR1"):
        return
    try:
        proc.send_signal(signal.SIGUSR1)
    except OSError:
        return
    time.sleep(wait)  # give the handler a beat to write the file


def _print_goodput_summary(goodput_dir: str, nranks: int) -> None:
    """Merge this job's rank journals and print the job-level ledger —
    the launcher's 'where did the training seconds go' report, the last
    thing an operator sees after a run. Filtered to ranks < nranks so a
    stale journal from an earlier, larger run sharing the directory
    cannot skew the summary."""
    try:
        from .. import goodput as _goodput

        merged = _goodput.load_journals(goodput_dir, ranks=range(nranks))
        if merged and (merged["steps"] or sum(merged["buckets"].values())):
            print("[launch] " + _goodput.render_summary(
                merged,
                title=f"goodput ({len(merged['ranks'])} rank(s))"
            ).replace("\n", "\n[launch] "), file=sys.stderr)
    except Exception as e:  # a summary failure must not mask the job rc
        print(f"[launch] goodput summary unavailable: {e}", file=sys.stderr)


def _print_memory_summary(memwatch_dir: str, nranks: int) -> None:
    """The memory half of the teardown report: merged per-rank peaks +
    leak counts from the memwatch journals. Called on its own dir
    resolution (PADDLE_TPU_MEMWATCH_DIR, falling back to the goodput
    directory) so an operator who exported only the memwatch dir still
    gets the table."""
    try:
        from .. import memwatch as _memwatch

        merged = _memwatch.load_journals(memwatch_dir, ranks=range(nranks))
        if merged and merged.get("lifetime_peak_bytes"):
            print("[launch] " + _memwatch.render_summary(
                merged,
                title=f"memory ({len(merged['ranks'])} rank(s))"
            ).replace("\n", "\n[launch] "), file=sys.stderr)
    except Exception as e:
        print(f"[launch] memory summary unavailable: {e}", file=sys.stderr)


def _print_dynamics_summary(dynamics_dir: str, nranks: int) -> None:
    """The training-quality third of the teardown report: merged
    per-rank final losses + anomaly episode counts from the dynamics
    journals, including the cross-rank loss-desync probe — under data
    parallelism a rank whose curve drifts from the others signals broken
    gradient synchronization, and this is the one place every rank's
    trajectory is in hand to check it."""
    try:
        from .. import dynamics as _dynamics

        merged = _dynamics.load_journals(dynamics_dir, ranks=range(nranks))
        if merged and merged.get("steps"):
            print("[launch] " + _dynamics.render_summary(
                merged,
                title=f"dynamics ({len(merged['ranks'])} rank(s))"
            ).replace("\n", "\n[launch] "), file=sys.stderr)
    except Exception as e:
        print(f"[launch] dynamics summary unavailable: {e}", file=sys.stderr)


def _print_serving_summary(serve_dir: str, nranks: int) -> None:
    """The serving quarter of the teardown report: merged per-replica
    SLO table (tokens/s across replicas, exact-merged TTFT/latency
    histograms for job-level p50/p99, occupancy) + the serving goodput
    buckets and span reconciliation — the last thing an operator sees
    after a --serve run."""
    try:
        from ..serving import ledger as _serving_ledger

        merged = _serving_ledger.load_journals(serve_dir,
                                               ranks=range(nranks))
        if merged and (merged.get("ticks")
                       or any((merged.get("requests") or {}).values())):
            print("[launch] " + _serving_ledger.render_summary(
                merged,
                title=f"serving ({len(merged['ranks'])} replica(s))"
            ).replace("\n", "\n[launch] "), file=sys.stderr)
            rec = merged.get("span_reconciliation") or {}
            if rec.get("verdict"):
                print(f"[launch] serving span reconciliation: "
                      f"{rec['verdict']} (ratio "
                      f"{rec.get('ratio')}, bound "
                      f"x{rec.get('bound_factor')})", file=sys.stderr)
            # the autoscaler's trail, when a capacity loop ran over this
            # job: current plan + the typed scale decisions
            auto = merged.get("autoscale") or {}
            plan = auto.get("plan") or {}
            decisions = [d for d in (auto.get("decisions") or [])
                         if isinstance(d, dict)]
            if plan or decisions:
                ups = sum(1 for d in decisions
                          if d.get("action") == "scale_up")
                downs = sum(1 for d in decisions
                            if d.get("action") == "scale_down")
                drained = sum(1 for d in decisions
                              if d.get("action") == "scale_down"
                              and d.get("drained"))
                print(f"[launch] autoscale: plan {plan.get('spec')} -> "
                      f"{plan.get('target_replicas')} replica(s) "
                      f"[{plan.get('verdict')}], {ups} scale-up(s) / "
                      f"{downs} scale-down(s) ({drained} drained)",
                      file=sys.stderr)
                for d in decisions[-4:]:
                    pred = d.get("predicted_slo_attainment")
                    real = d.get("realized_slo_attainment")
                    print(f"[launch]   {d.get('action')}: "
                          f"{d.get('from_replicas')}->"
                          f"{d.get('to_replicas')} ({d.get('reason')})"
                          + (f" predicted={pred} realized={real}"
                             if pred is not None or real is not None
                             else ""), file=sys.stderr)
    except Exception as e:
        print(f"[launch] serving summary unavailable: {e}", file=sys.stderr)


def _stale_ranks(endpoints: List[str], timeout: float) -> List[int]:
    """Union of trainer ids any pserver's heartbeat monitor considers
    dead (server.py do_heartbeat_status — the supervisor-side consumer
    of heart_beat_monitor.h)."""
    import numpy as np

    from .ps.rpc import PSClient

    dead = set()
    for ep in endpoints:
        try:
            # bounded connect AND recv deadlines: the supervisor's
            # liveness must not depend on a hung pserver
            client = PSClient(ep, timeout=5.0, recv_timeout=5.0)
            rep = client.call("heartbeat_status", timeout=timeout)
            dead.update(int(t) for t in np.asarray(rep["dead"]).ravel())
            client.close()
        except Exception:
            continue  # an unreachable server cannot vote
    return sorted(dead)


def _launch_once(args, restart_count: int) -> int:
    ips = args.ips.split(",")
    endpoints = get_cluster_endpoints(ips, args.nproc_per_node, args.started_port)
    nranks = len(endpoints)
    local_base = args.host_rank * args.nproc_per_node

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    trace_dir = args.trace_dir
    if trace_dir:
        trace_dir = os.path.abspath(trace_dir)
        os.makedirs(trace_dir, exist_ok=True)
    goodput_dir = args.goodput_dir or trace_dir
    if goodput_dir:
        goodput_dir = os.path.abspath(goodput_dir)
        os.makedirs(goodput_dir, exist_ok=True)
    serve_dir = None
    if args.serve:
        serve_dir = args.serve_dir or goodput_dir or trace_dir
        if serve_dir:
            serve_dir = os.path.abspath(serve_dir)
            os.makedirs(serve_dir, exist_ok=True)
    seen_dumps: set = set()

    respawns = [0] * args.nproc_per_node
    hb_eps = [e for e in args.heartbeat_endpoints.split(",") if e]

    def spawn(local_rank: int, attempt: int) -> subprocess.Popen:
        rank = local_base + local_rank
        env = dict(os.environ)
        env.update(
            {
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(nranks),
                "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
                "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
                "FLAGS_selected_tpus": str(local_rank),
                # job-level whole-set restarts and per-rank respawns are
                # DISTINCT attempt identities (auto-checkpoint dirs/logs)
                "PADDLE_RESTART_COUNT": str(restart_count),
                "PADDLE_RESPAWN_COUNT": str(attempt),
                # the launcher-swept collective epoch: every KV key the
                # eager collectives publish is scoped by it, so attempt
                # N+1 can never pair against attempt N's stale payloads
                # still sitting in a surviving coordination service
                "PADDLE_TPU_COLL_EPOCH": str(restart_count),
            }
        )
        if args.ckpt_dir:
            # full-state recovery plumbing: every rank checkpoints its
            # training state here and auto-resumes from it on respawn
            ckpt_dir = os.path.abspath(args.ckpt_dir)
            os.makedirs(ckpt_dir, exist_ok=True)
            env["PADDLE_TPU_CKPT_DIR"] = ckpt_dir
        else:
            # an unset flag sheds the inherited env (the PR-4 idiom): a
            # supervisor's stale dir must not resurrect on the children
            env.pop("PADDLE_TPU_CKPT_DIR", None)
        # DP comms recipe plumbing: one launcher flag configures every
        # rank's gradient-sync behavior (distributed/comms.py reads the
        # env live; the teardown goodput summary's `collective` row is
        # where the effect shows up)
        if args.dp_bucket_mb is not None:
            env["PADDLE_TPU_DP_BUCKET_MB"] = str(args.dp_bucket_mb)
        if args.dp_quantize is not None:
            env["PADDLE_TPU_DP_QUANTIZE"] = (
                "" if args.dp_quantize == "none" else args.dp_quantize)
        if args.dp_overlap is not None:
            env["PADDLE_TPU_DP_OVERLAP"] = args.dp_overlap
        if trace_dir:
            # distributed-tracing env plumbing: each rank traces itself
            # (profiler.py auto-enables) and writes trace.rank<k>.json +
            # flight dumps into the shared dir
            env["PADDLE_TPU_TRACE_DIR"] = trace_dir
            if "PADDLE_TPU_TRACE" not in env:
                env["PADDLE_TPU_TRACE"] = "1"
        if goodput_dir:
            # each rank journals its goodput ledger; the launcher merges
            # and prints the job-level summary at teardown. The memory
            # ledger (memwatch.rank<k>.json) shares the directory unless
            # the operator pointed PADDLE_TPU_MEMWATCH_DIR elsewhere
            env["PADDLE_TPU_GOODPUT_DIR"] = goodput_dir
            env.setdefault("PADDLE_TPU_MEMWATCH_DIR", goodput_dir)
            # the training-dynamics journal (dynamics.rank<k>.jsonl)
            # shares the directory too: the teardown merge runs the
            # cross-rank loss-desync probe over it
            env.setdefault("PADDLE_TPU_DYNAMICS_DIR", goodput_dir)
        else:
            # an explicitly-disabled flag must also shed the inherited
            # env, or the children re-enable what the operator turned off
            env.pop("PADDLE_TPU_GOODPUT_DIR", None)
        if serve_dir:
            # serving-replica plumbing: each replica journals its SLO
            # ledger (serving.rank<k>.json) into the shared dir; the
            # supervisor merges and prints the job SLO summary at
            # teardown. Per-replica /status ports ride --status_port.
            env["PADDLE_TPU_SERVE_DIR"] = serve_dir
        elif not args.serve:
            # not a serving job: shed any inherited serving env so
            # training children don't journal a phantom serving plane
            env.pop("PADDLE_TPU_SERVE_DIR", None)
        if args.status_port:
            # live per-rank introspection: rank k serves base+k
            # (paddle_tpu.status auto-binds at import). The printed link
            # honors the bind interface: loopback unless the operator
            # opted into external scraping via PADDLE_TPU_STATUS_HOST
            port = args.status_port + rank
            env["PADDLE_TPU_STATUS_PORT"] = str(port)
            bind = env.get("PADDLE_TPU_STATUS_HOST", "127.0.0.1")
            ip = (endpoints[rank].rsplit(":", 1)[0]
                  if bind not in ("127.0.0.1", "localhost") else bind)
            print(f"[launch] rank {rank} status: http://{ip}:{port}/status "
                  f"(also /metrics, /healthz)", file=sys.stderr)
        else:
            # --status_port 0 with the env exported: a per-rank port was
            # NOT assigned, so all ranks would fight over the inherited
            # one — disable instead
            env.pop("PADDLE_TPU_STATUS_PORT", None)
        cmd = [sys.executable, "-u", args.training_script] + args.training_script_args
        log = (
            open(os.path.join(args.log_dir, f"workerlog.{rank}"), "a")
            if args.log_dir
            else None
        )
        return subprocess.Popen(cmd, env=env, stdout=log, stderr=log)

    procs: List[subprocess.Popen] = [
        spawn(lr, restart_count) for lr in range(args.nproc_per_node)
    ]
    spawn_time = [time.monotonic()] * args.nproc_per_node

    # supervise (reference launch_utils.py TrainerProc watch loop).
    # restart_all: fail fast, the caller relaunches the set.
    # respawn_worker: the failed rank alone restarts in place (PS-mode
    # single-worker rejoin, the r4 verdict gap); hung workers flagged by
    # the pserver heartbeat are killed and respawned the same way.
    rc = 0
    last_hb = time.monotonic()
    try:
        alive = True
        while alive:
            alive = False
            for lr, p in enumerate(procs):
                code = p.poll()
                if code is None:
                    alive = True
                elif code != 0:
                    if trace_dir:  # a crashed rank may have dumped on TERM
                        _collect_flight_dumps(trace_dir, seen_dumps)
                    # serving replicas are independent by construction
                    # (no collective membership): a dead replica warm-
                    # restarts IN PLACE (params reload + serving-journal
                    # resume + router re-admission via /healthz) while
                    # the survivors keep serving — restart_all would
                    # tear down healthy replicas mid-traffic for no
                    # membership reason
                    if ((args.elastic_mode == "respawn_worker"
                         or (args.serve and args.elastic_retries > 0))
                            and respawns[lr] < args.elastic_retries):
                        respawns[lr] += 1
                        procs[lr] = spawn(lr, respawns[lr])
                        spawn_time[lr] = time.monotonic()
                        alive = True
                        continue
                    rc = code
                    for q in procs:
                        if q.poll() is None:
                            q.send_signal(signal.SIGTERM)
                    alive = False
                    break
            if (alive and hb_eps
                    and time.monotonic() - last_hb >= args.heartbeat_timeout / 3):
                last_hb = time.monotonic()
                for dead_rank in _stale_ranks(hb_eps, args.heartbeat_timeout):
                    lr = dead_rank - local_base
                    if not (0 <= lr < len(procs)) or procs[lr].poll() is not None:
                        continue
                    # a freshly respawned worker needs time for imports +
                    # first compile before its first beat clears the
                    # server's stale timestamp — grace-period it
                    if time.monotonic() - spawn_time[lr] < args.heartbeat_timeout:
                        continue
                    if args.elastic_mode != "respawn_worker":
                        # collective mode: membership must stay consistent
                        # — treat the hung rank as a whole-set failure
                        rc = 1
                        for q in procs:
                            if q.poll() is None:
                                q.send_signal(signal.SIGTERM)
                        alive = False
                        break
                    if respawns[lr] >= args.elastic_retries:
                        continue
                    if trace_dir:
                        # the rank is hung, not dead: ask for a flight
                        # dump (stacks + last spans) before killing it
                        _request_flight_dump(procs[lr])
                    procs[lr].terminate()
                    try:
                        procs[lr].wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        # SIGTERM blocked (truly hung): escalate
                        procs[lr].kill()
                        try:
                            procs[lr].wait(timeout=10)
                        except subprocess.TimeoutExpired:
                            continue  # unkillable; leave it to the OS
                    respawns[lr] += 1
                    _clear_heartbeat(hb_eps, dead_rank)
                    if trace_dir:
                        _collect_flight_dumps(trace_dir, seen_dumps)
                    procs[lr] = spawn(lr, respawns[lr])
                    spawn_time[lr] = time.monotonic()
            time.sleep(1)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        if trace_dir:
            # SIGTERM handlers (monitor.install_dump_handlers) may still
            # be writing: one grace beat, then surface everything new
            time.sleep(0.5)
            _collect_flight_dumps(trace_dir, seen_dumps)
        mw_dir = os.environ.get("PADDLE_TPU_MEMWATCH_DIR") or goodput_dir
        dyn_dir = os.environ.get("PADDLE_TPU_DYNAMICS_DIR") or goodput_dir
        if goodput_dir or mw_dir or dyn_dir:
            # atexit journal flushes may trail the SIGTERM by a beat
            if not trace_dir:
                time.sleep(0.5)
        if goodput_dir:
            _print_goodput_summary(goodput_dir, nranks)
        if mw_dir:
            _print_memory_summary(mw_dir, nranks)
        if dyn_dir:
            _print_dynamics_summary(dyn_dir, nranks)
        if serve_dir:
            _print_serving_summary(serve_dir, nranks)
    return rc


def main(argv=None):
    sys.exit(launch(_parse_args(argv)))


if __name__ == "__main__":
    main()
