"""Cluster launcher: `python -m paddle_tpu.distributed.launch train.py`.

Counterpart of /root/reference/python/paddle/distributed/launch.py:214 and
fleet/launch_utils.py:409-440 — builds the cluster map and spawns one
worker process per *host* (not per chip: on TPU all local chips belong to
one process; SURVEY.md §7.2.6) with the same PADDLE_* env protocol:
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_CURRENT_ENDPOINT /
PADDLE_TRAINER_ENDPOINTS. Workers rendezvous via jax.distributed
(paddle_tpu.parallel.env.init_parallel_env) instead of NCCL-id broadcast.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List


def _parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument(
        "--ips", type=str, default="127.0.0.1",
        help="comma-separated host ips of the job (reference --cluster_node_ips)",
    )
    p.add_argument(
        "--nproc_per_node", type=int, default=1,
        help="worker processes per host; >1 only for CPU-simulation runs "
        "(one process per TPU host owns all its chips)",
    )
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument(
        "--elastic_retries", type=int, default=0,
        help="restart the whole local worker set up to N times after a "
        "failure (job-level elasticity; workers resume from their "
        "auto-checkpoints — incubate.checkpoint.auto_checkpoint)",
    )
    p.add_argument("--host_rank", type=int, default=int(os.environ.get("POD_INDEX", "0")))
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def get_cluster_endpoints(ips: List[str], nproc: int, port: int) -> List[str]:
    eps = []
    for ip in ips:
        for i in range(nproc):
            eps.append(f"{ip}:{port + i}")
    return eps


def launch(args) -> int:
    """Spawn + supervise the local workers; with --elastic_retries, a
    failed worker set is torn down and restarted (the reference
    launch_utils.py:409-440 watch loop is fail-fast only; restart is the
    elastic extension, with auto-checkpoint providing resume)."""
    attempts = 0
    while True:
        rc = _launch_once(args, attempts)
        if rc == 0 or attempts >= args.elastic_retries:
            return rc
        attempts += 1
        time.sleep(1.0)


def _launch_once(args, restart_count: int) -> int:
    ips = args.ips.split(",")
    endpoints = get_cluster_endpoints(ips, args.nproc_per_node, args.started_port)
    nranks = len(endpoints)
    local_base = args.host_rank * args.nproc_per_node

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    procs: List[subprocess.Popen] = []
    for local_rank in range(args.nproc_per_node):
        rank = local_base + local_rank
        env = dict(os.environ)
        env.update(
            {
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(nranks),
                "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
                "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
                "FLAGS_selected_tpus": str(local_rank),
                "PADDLE_RESTART_COUNT": str(restart_count),
            }
        )
        cmd = [sys.executable, "-u", args.training_script] + args.training_script_args
        log = (
            open(os.path.join(args.log_dir, f"workerlog.{rank}"), "w")
            if args.log_dir
            else None
        )
        procs.append(subprocess.Popen(cmd, env=env, stdout=log, stderr=log))

    # supervise: fail fast on any child failure (reference
    # launch_utils.py TrainerProc watch loop)
    rc = 0
    try:
        alive = True
        while alive:
            alive = False
            for p in procs:
                code = p.poll()
                if code is None:
                    alive = True
                elif code != 0:
                    rc = code
                    for q in procs:
                        if q.poll() is None:
                            q.send_signal(signal.SIGTERM)
                    alive = False
                    break
            time.sleep(1)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
    return rc


def main(argv=None):
    sys.exit(launch(_parse_args(argv)))


if __name__ == "__main__":
    main()
