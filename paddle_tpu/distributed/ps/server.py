"""Parameter server: host-side dense blocks + sparse row tables.

Counterpart of the reference pserver runtime: the listen_and_serv event
loop (operators/distributed_ops/listen_and_serv_op.cc — blocking server
that runs optimize blocks per received grad), the large-scale sparse KV
(operators/distributed/large_scale_kv.h — per-row initialized embedding
shards), and the request handlers (request_handler_impl.cc
RequestSend/RequestGet/RequestPrefetch).

Sync semantics (a_sync=False): gradients from all trainers accumulate
per step; the optimizer applies once the barrier count fills — exactly
the reference's sync-mode grad aggregation (dist_transpiler sync_mode,
grad merge on the server's optimize block), so training is
step-equivalent to single-process full-batch SGD/Adam on the averaged
gradient.

Async (a_sync=True): apply-on-arrival, no barrier — the reference
AsyncCommunicator/geo path's staleness model.
"""
from __future__ import annotations

import socket
import threading
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from .rpc import recv_msg, send_msg


class _DenseSlot:
    def __init__(self, value: np.ndarray):
        self.value = value.astype(np.float32)
        self.grad_acc = np.zeros_like(self.value)
        self.grad_count = 0
        self.state: Dict[str, np.ndarray] = {}


class _SparseTable:
    """Row-indexed embedding table with lazy row init (large_scale_kv.h:
    rows materialize on first touch, initializer attr-driven)."""

    def __init__(self, dim: int, initializer: Optional[Callable] = None, seed: int = 0):
        self.dim = dim
        self.rows: Dict[int, np.ndarray] = {}
        self.state: Dict[int, Dict[str, np.ndarray]] = {}
        self.seed = seed
        # per-ROW-id deterministic init: first-touch ORDER must not change
        # row values, or trainer interleaving breaks run-to-run parity
        self._init_row = initializer or (
            lambda rid: np.random.RandomState(
                (self.seed * 1000003 + rid * 2654435761) % (2**31 - 1)
            ).uniform(-0.05, 0.05, size=(dim,)).astype(np.float32)
        )

    def _init(self, rid: int = 0) -> np.ndarray:
        return self._init_row(rid)

    def lookup(self, ids: np.ndarray) -> np.ndarray:
        out = np.empty((len(ids), self.dim), np.float32)
        for i, rid in enumerate(ids.tolist()):
            row = self.rows.get(rid)
            if row is None:
                row = self.rows[rid] = self._init(rid)
            out[i] = row
        return out


class ParameterServer:
    """One shard of the global parameter space (one `--pservers` endpoint).

    Methods map 1:1 onto the reference request handlers:
    init_dense/init_table <- the startup program the transpiler builds per
    pserver; push_dense/push_sparse <- RequestSend; pull_dense <-
    RequestGet; pull_sparse <- RequestPrefetch; barrier <- the
    send/fetch barrier ops.
    """

    def __init__(self, num_trainers: int = 1, sync: bool = True,
                 optimizer: str = "sgd", lr: float = 0.01,
                 optimizer_attrs: Optional[Dict[str, float]] = None):
        self.num_trainers = num_trainers
        self.sync = sync
        self.optimizer = optimizer
        self.lr = lr
        self.opt_attrs = dict(optimizer_attrs or {})
        self.dense: Dict[str, _DenseSlot] = {}
        self.tables: Dict[str, _SparseTable] = {}
        # sync mode: sparse grads accumulate here until the barrier fills,
        # then apply as ONE optimizer step per row — per-arrival Adam
        # updates on half-gradients would advance t twice per step and
        # diverge from the single-process trajectory
        self._pending_sparse: Dict[str, Dict[int, np.ndarray]] = {}
        self._lock = threading.Condition()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._stopped = threading.Event()

    # -- request handlers ----------------------------------------------
    def handle(self, method: str, p: Dict[str, Any]) -> Dict[str, Any]:
        fn = getattr(self, "do_" + method, None)
        if fn is None:
            raise RuntimeError(f"unknown PS method {method!r}")
        return fn(p) or {}

    def do_init_dense(self, p):
        with self._lock:
            if p["name"] not in self.dense:  # first trainer wins
                self.dense[p["name"]] = _DenseSlot(p["value"])

    def do_init_table(self, p):
        with self._lock:
            if p["name"] not in self.tables:
                self.tables[p["name"]] = _SparseTable(
                    int(p["dim"]), seed=int(p.get("seed", 0))
                )

    def do_push_dense(self, p):
        name = p["name"]
        with self._lock:
            slot = self.dense[name]
            slot.grad_acc += p["grad"].astype(np.float32)
            slot.grad_count += 1
            if self.sync:
                if slot.grad_count >= self.num_trainers:
                    self._apply_dense(name, slot, slot.grad_acc / slot.grad_count)
                    slot.grad_acc[...] = 0.0
                    slot.grad_count = 0
                    self._lock.notify_all()
            else:
                self._apply_dense(name, slot, slot.grad_acc)
                slot.grad_acc[...] = 0.0
                slot.grad_count = 0

    def do_pull_dense(self, p):
        with self._lock:
            if self.sync:
                # a pull between push and barrier must see the updated
                # value; _apply_dense runs under the same lock, and sync
                # trainers only pull after the step barrier, so no wait
                # is needed here
                pass
            return {"value": self.dense[p["name"]].value}

    def do_push_sparse(self, p):
        name, ids, grad = p["name"], p["ids"], p["grad"].astype(np.float32)
        with self._lock:
            table = self.tables[name]
            # merge duplicate ids first (reference MergeSelectedRows)
            uniq, inv = np.unique(ids, return_inverse=True)
            merged = np.zeros((len(uniq), table.dim), np.float32)
            np.add.at(merged, inv, grad)
            if self.sync:
                pend = self._pending_sparse.setdefault(name, {})
                scale = 1.0 / self.num_trainers
                for i, rid in enumerate(uniq.tolist()):
                    if rid in pend:
                        pend[rid] = pend[rid] + merged[i] * scale
                    else:
                        pend[rid] = merged[i] * scale
            else:
                for i, rid in enumerate(uniq.tolist()):
                    row = table.rows.get(rid)
                    if row is None:
                        row = table.rows[rid] = table._init(rid)
                    self._apply_sparse_row(table, rid, row, merged[i])

    def _flush_pending_sparse_locked(self):
        for name, pend in self._pending_sparse.items():
            table = self.tables[name]
            for rid, grad in pend.items():
                row = table.rows.get(rid)
                if row is None:
                    row = table.rows[rid] = table._init(rid)
                self._apply_sparse_row(table, rid, row, grad)
        self._pending_sparse.clear()

    def do_pull_sparse(self, p):
        with self._lock:
            return {"value": self.tables[p["name"]].lookup(p["ids"].ravel())}

    def do_barrier(self, p):
        """All-trainer rendezvous (reference send_barrier/fetch_barrier).
        The last arrival flushes the step's accumulated sparse grads, so
        post-barrier pulls see exactly one optimizer step per row."""
        with self._lock:
            gen = self._barrier_gen
            self._barrier_count += 1
            if self._barrier_count >= self.num_trainers:
                if self.sync:
                    self._flush_pending_sparse_locked()
                self._barrier_count = 0
                self._barrier_gen += 1
                self._lock.notify_all()
            else:
                while self._barrier_gen == gen and not self._stopped.is_set():
                    self._lock.wait(timeout=1.0)

    def do_state(self, p):
        with self._lock:
            return {
                "dense": ",".join(sorted(self.dense)),
                "tables": ",".join(sorted(self.tables)),
                "rows": sum(len(t.rows) for t in self.tables.values()),
            }

    def do_stop(self, p):
        self._stopped.set()
        with self._lock:
            self._lock.notify_all()

    # -- optimizers -----------------------------------------------------
    def _apply_dense(self, name: str, slot: _DenseSlot, grad: np.ndarray):
        if self.optimizer == "sgd":
            slot.value -= self.lr * grad
        elif self.optimizer == "adam":
            st = slot.state
            if not st:
                st["m"] = np.zeros_like(slot.value)
                st["v"] = np.zeros_like(slot.value)
                st["t"] = np.zeros((), np.int64)
            b1 = self.opt_attrs.get("beta1", 0.9)
            b2 = self.opt_attrs.get("beta2", 0.999)
            eps = self.opt_attrs.get("epsilon", 1e-8)
            st["t"] = st["t"] + 1
            st["m"] = b1 * st["m"] + (1 - b1) * grad
            st["v"] = b2 * st["v"] + (1 - b2) * grad * grad
            mhat = st["m"] / (1 - b1 ** int(st["t"]))
            vhat = st["v"] / (1 - b2 ** int(st["t"]))
            slot.value -= self.lr * mhat / (np.sqrt(vhat) + eps)
        else:
            raise RuntimeError(f"pserver optimizer {self.optimizer!r} unsupported")

    def _apply_sparse_row(self, table: _SparseTable, rid: int, row: np.ndarray,
                          grad: np.ndarray):
        if self.optimizer == "sgd":
            row -= self.lr * grad
        elif self.optimizer == "adam":
            st = table.state.setdefault(rid, {})
            if not st:
                st["m"] = np.zeros_like(row)
                st["v"] = np.zeros_like(row)
                st["t"] = 0
            b1 = self.opt_attrs.get("beta1", 0.9)
            b2 = self.opt_attrs.get("beta2", 0.999)
            eps = self.opt_attrs.get("epsilon", 1e-8)
            st["t"] += 1
            st["m"] = b1 * st["m"] + (1 - b1) * grad
            st["v"] = b2 * st["v"] + (1 - b2) * grad * grad
            mhat = st["m"] / (1 - b1 ** st["t"])
            vhat = st["v"] / (1 - b2 ** st["t"])
            row -= self.lr * mhat / (np.sqrt(vhat) + eps)
        else:
            raise RuntimeError(f"pserver optimizer {self.optimizer!r} unsupported")


def start_server(endpoint: str, server: ParameterServer,
                 block: bool = False) -> Tuple[threading.Thread, Callable[[], None]]:
    """The listen_and_serv event loop (listen_and_serv_op.cc): accept
    connections, dispatch framed requests to the handlers until stopped.
    Returns (thread, shutdown) when block=False."""
    host, port = endpoint.rsplit(":", 1)
    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind((host, int(port)))
    lsock.listen(64)
    lsock.settimeout(0.5)

    def conn_loop(sock):
        with sock:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while not server._stopped.is_set():
                try:
                    method, payload = recv_msg(sock)
                except (ConnectionError, OSError):
                    return
                try:
                    reply = server.handle(method, payload)
                    send_msg(sock, "ok", reply)
                except Exception as e:  # surface handler errors to the peer
                    try:
                        send_msg(sock, "error", {"message": f"{type(e).__name__}: {e}"})
                    except OSError:
                        return
                if method == "stop":
                    return

    def accept_loop():
        with lsock:
            while not server._stopped.is_set():
                try:
                    sock, _ = lsock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                threading.Thread(target=conn_loop, args=(sock,), daemon=True).start()

    if block:
        accept_loop()
        return None, lambda: None
    thread = threading.Thread(target=accept_loop, daemon=True)
    thread.start()

    def shutdown():
        server._stopped.set()
        try:
            lsock.close()
        except OSError:
            pass

    return thread, shutdown
