"""Parameter server: host-side dense blocks + vectorized sparse row tables.

Counterpart of the reference pserver runtime: the listen_and_serv event
loop (operators/distributed_ops/listen_and_serv_op.cc — blocking server
that runs optimize blocks per received grad), the large-scale sparse KV
(operators/distributed/large_scale_kv.h — per-row initialized embedding
shards), the request handlers (request_handler_impl.cc
RequestSend/RequestGet/RequestPrefetch/RequestCheckpoint), and the geo
delta path (distributed/communicator.h:396 GeoCommunicator).

Sync semantics (a_sync=False): gradients from all trainers accumulate
per step; the optimizer applies once the barrier count fills — exactly
the reference's sync-mode grad aggregation, so training is
step-equivalent to single-process full-batch SGD/Adam on the averaged
gradient. Async: apply-on-arrival. Geo: the server holds the global
params; trainers train locally and push parameter DELTAS, applied
additively (no server-side optimizer).

Data plane: sparse tables store rows in a growable ndarray block with an
id->slot map; lookups/updates are bulk gathers/scatters and the Adam rule
is applied vectorized over the touched slots (the round-3 per-row dict
loops are gone — see tests/test_ps_throughput.py for the measured
speedup). Sparse traffic locks per TABLE; only barrier/dense bookkeeping
takes the server lock.
"""
from __future__ import annotations

import os
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ... import monitor as _monitor
from ... import profiler as _profiler
from .rpc import TRACE_KEY, recv_msg_sized, send_msg

# server-side request telemetry (per-process: each pserver reports its
# own handler counts/latency/bytes — the serve-side half of the absolute
# msgs/s + MB/s numbers)
_M_SREQ = _monitor.counter(
    "ps_server_requests_total", "PS requests handled", ("method",))
_M_SREQ_T = _monitor.histogram(
    "ps_server_request_seconds", "PS handler latency (incl. barrier waits)",
    ("method",))
_M_SIN = _monitor.counter(
    "ps_server_bytes_in_total", "PS request bytes received", ("method",))
_M_SOUT = _monitor.counter(
    "ps_server_bytes_out_total", "PS reply bytes sent", ("method",))


class _DenseSlot:
    def __init__(self, value: np.ndarray):
        self.value = value.astype(np.float32)
        self.grad_acc = np.zeros_like(self.value)
        self.grad_count = 0
        self.state: Dict[str, np.ndarray] = {}


class _SparseTable:
    """Row tables as one contiguous ndarray block (large_scale_kv.h rows,
    re-laid-out for bulk ops). id->slot is the only per-id Python
    structure; values/adam state live in (capacity, dim) arrays."""

    def __init__(self, dim: int, seed: int = 0, capacity: int = 1024):
        self.dim = dim
        self.seed = seed
        self.data = np.zeros((capacity, dim), np.float32)
        self.ids = np.zeros(capacity, np.int64)
        self.slot_of: Dict[int, int] = {}
        self.n = 0
        # adam state, allocated on first adam apply
        self.m: Optional[np.ndarray] = None
        self.v: Optional[np.ndarray] = None
        self.t: Optional[np.ndarray] = None
        self.lock = threading.RLock()

    def _init_rows(self, rids: np.ndarray) -> np.ndarray:
        """Vectorized per-row deterministic init (counter-based hash ->
        uniform[-0.05, 0.05]); first-touch ORDER cannot change values."""
        rid = rids.astype(np.uint64)[:, None]
        col = np.arange(self.dim, dtype=np.uint64)[None, :]
        h = (rid * np.uint64(2654435761)
             + col * np.uint64(0x9E3779B9)
             + np.uint64((self.seed * 1000003) & 0xFFFFFFFF))
        h ^= h >> np.uint64(33)
        h *= np.uint64(0xFF51AFD7ED558CCD)
        h ^= h >> np.uint64(29)
        u = (h >> np.uint64(40)).astype(np.float64) / float(1 << 24)
        return ((u - 0.5) * 0.1).astype(np.float32)

    def _grow(self, need: int):
        cap = len(self.data)
        if self.n + need <= cap:
            return
        new_cap = max(cap * 2, self.n + need)
        for name in ("data", "m", "v"):
            arr = getattr(self, name)
            if arr is not None:
                na = np.zeros((new_cap, arr.shape[1]), arr.dtype)
                na[: len(arr)] = arr
                setattr(self, name, na)
        nids = np.zeros(new_cap, np.int64)
        nids[: len(self.ids)] = self.ids
        self.ids = nids
        if self.t is not None:
            nt = np.zeros(new_cap, np.int64)
            nt[: len(self.t)] = self.t
            self.t = nt

    def ensure(self, uniq_ids: np.ndarray) -> np.ndarray:
        """SORTED unique id array -> slot array, materializing missing rows
        in bulk. The id->slot map is a sorted-array searchsorted (fully
        vectorized); inserts merge-sort the new ids in (rare after
        warmup). `slot_of` mirrors it for save/load + diagnostics."""
        uniq_ids = np.asarray(uniq_ids, np.int64)
        if not hasattr(self, "_sorted_ids"):
            self._sorted_ids = np.empty(0, np.int64)
            self._sorted_slots = np.empty(0, np.int64)
        pos = np.searchsorted(self._sorted_ids, uniq_ids)
        if len(self._sorted_ids):
            pos_c = np.minimum(pos, len(self._sorted_ids) - 1)
            found = self._sorted_ids[pos_c] == uniq_ids
        else:
            found = np.zeros(len(uniq_ids), bool)
        missing = uniq_ids[~found]
        if missing.size:
            k = len(missing)
            self._grow(k)
            sl = np.arange(self.n, self.n + k)
            self.data[sl] = self._init_rows(missing)
            self.ids[sl] = missing
            self.n += k
            ins = np.searchsorted(self._sorted_ids, missing)
            self._sorted_ids = np.insert(self._sorted_ids, ins, missing)
            self._sorted_slots = np.insert(self._sorted_slots, ins, sl)
            for rid, s in zip(missing.tolist(), sl.tolist()):
                self.slot_of[rid] = s
            pos = np.searchsorted(self._sorted_ids, uniq_ids)
        return self._sorted_slots[pos]

    def lookup(self, ids: np.ndarray) -> np.ndarray:
        with self.lock:
            uniq, inv = np.unique(ids, return_inverse=True)
            slots = self.ensure(uniq)
            return self.data[slots][inv]

    def write(self, ids: np.ndarray, values: np.ndarray) -> None:
        """Assign rows, LAST duplicate wins (lookup_sparse_table_write)."""
        ids = np.asarray(ids, np.int64).ravel()
        values = np.asarray(values, np.float32).reshape(ids.size, self.dim)
        with self.lock:
            uniq, ridx = np.unique(ids[::-1], return_index=True)
            slots = self.ensure(uniq)
            self.data[slots] = values[::-1][ridx]

    def apply(self, uniq_ids: np.ndarray, grads: np.ndarray,
              optimizer: str, lr: float, attrs: Dict[str, float]):
        """One vectorized optimizer step over the touched rows."""
        with self.lock:
            slots = self.ensure(uniq_ids)
            if optimizer == "sgd":
                self.data[slots] -= lr * grads
                return
            if optimizer != "adam":
                raise RuntimeError(f"pserver optimizer {optimizer!r} unsupported")
            if self.m is None:
                cap = len(self.data)
                self.m = np.zeros((cap, self.dim), np.float32)
                self.v = np.zeros((cap, self.dim), np.float32)
                self.t = np.zeros(cap, np.int64)
            # fp32 constants: Python-float scalars silently promote the
            # whole update to float64 (2x memory traffic)
            b1 = np.float32(attrs.get("beta1", 0.9))
            b2 = np.float32(attrs.get("beta2", 0.999))
            eps = np.float32(attrs.get("epsilon", 1e-8))
            lr32 = np.float32(lr)
            one = np.float32(1.0)
            t = self.t[slots] + 1
            self.t[slots] = t
            tf = t.astype(np.float32)
            grads = np.asarray(grads, np.float32)
            m = self.m[slots]
            m *= b1
            m += (one - b1) * grads
            v = self.v[slots]
            v *= b2
            v += (one - b2) * (grads * grads)
            self.m[slots] = m
            self.v[slots] = v
            corr = (one - b1 ** tf)[:, None]
            corr2 = (one - b2 ** tf)[:, None]
            self.data[slots] -= lr32 * (m / corr) / (np.sqrt(v / corr2) + eps)


def _new_table(dim: int, seed: int = 0):
    """Native (C++ csrc/ps_table.cc) table when built, Python otherwise —
    identical init hash and checkpoint format, so mixed fleets work."""
    from . import native_table

    if native_table.available():
        return native_table.NativeSparseTable(dim, seed=seed)
    return _SparseTable(dim, seed=seed)


class ParameterServer:
    """One shard of the global parameter space (one `--pservers` endpoint).

    Methods map 1:1 onto the reference request handlers:
    init_dense/init_table <- the startup program the transpiler builds per
    pserver; push_dense/push_sparse <- RequestSend; pull_dense <-
    RequestGet; pull_sparse <- RequestPrefetch; barrier <- the send/fetch
    barrier ops; save/load <- checkpoint_notify_op.cc / recv_save_op.cc;
    push_geo <- the GeoCommunicator delta path.
    """

    def __init__(self, num_trainers: int = 1, sync: bool = True,
                 optimizer: str = "sgd", lr: float = 0.01,
                 optimizer_attrs: Optional[Dict[str, float]] = None):
        self.num_trainers = num_trainers
        self.sync = sync
        self.optimizer = optimizer
        self.lr = lr
        self.opt_attrs = dict(optimizer_attrs or {})
        self.dense: Dict[str, _DenseSlot] = {}
        self.tables: Dict[str, _SparseTable] = {}
        # sync mode: (ids, scaled-grad) pushes buffer per table until the
        # barrier fills, then merge + ONE vectorized optimizer step per
        # row — per-arrival Adam on half-gradients would advance t twice
        # per step and diverge from the single-process trajectory
        self._pending_sparse: Dict[str, List[Tuple[np.ndarray, np.ndarray]]] = {}
        self._lock = threading.Condition()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._stopped = threading.Event()
        self._heartbeats: Dict[int, float] = {}

    # -- request handlers ----------------------------------------------
    def handle(self, method: str, p: Dict[str, Any]) -> Dict[str, Any]:
        fn = getattr(self, "do_" + method, None)
        if fn is None:
            raise RuntimeError(f"unknown PS method {method!r}")
        return fn(p) or {}

    def do_init_dense(self, p):
        with self._lock:
            if p["name"] not in self.dense:  # first trainer wins
                self.dense[p["name"]] = _DenseSlot(p["value"])

    def do_init_table(self, p):
        with self._lock:
            if p["name"] not in self.tables:
                self.tables[p["name"]] = _new_table(
                    int(p["dim"]), seed=int(p.get("seed", 0))
                )

    def do_push_dense(self, p):
        name = p["name"]
        lr = p.get("lr")  # per-step lr shipped in the payload (schedules)
        with self._lock:
            slot = self.dense[name]
            slot.grad_acc += p["grad"].astype(np.float32)
            slot.grad_count += 1
            if self.sync:
                if slot.grad_count >= self.num_trainers:
                    self._apply_dense(name, slot, slot.grad_acc / slot.grad_count, lr)
                    slot.grad_acc[...] = 0.0
                    slot.grad_count = 0
                    self._lock.notify_all()
            else:
                self._apply_dense(name, slot, slot.grad_acc, lr)
                slot.grad_acc[...] = 0.0
                slot.grad_count = 0

    def do_push_geo(self, p):
        """Geo mode: additive parameter delta (communicator.h:396
        GeoCommunicator::Send semantics — server state is the sum of all
        trainers' local progress)."""
        with self._lock:
            slot = self.dense.get(p["name"])
            if slot is None:
                slot = self.dense[p["name"]] = _DenseSlot(
                    np.zeros_like(p["delta"], np.float32)
                )
            slot.value += p["delta"].astype(np.float32)
            # copy: the reply serializes outside the lock while other
            # trainers' deltas mutate slot.value in place
            return {"value": slot.value.copy()}

    def do_pull_dense(self, p):
        with self._lock:
            # copy: the reply is serialized after the lock is released, and
            # async _apply_dense mutates slot.value in place concurrently —
            # without the snapshot a puller can see a torn mixed-step tensor
            return {"value": self.dense[p["name"]].value.copy()}

    def do_push_sparse(self, p):
        name, ids, grad = p["name"], p["ids"], p["grad"].astype(np.float32)
        table = self.tables[name]
        lr = p.get("lr")
        # merge duplicate ids first (reference MergeSelectedRows)
        uniq, inv = np.unique(ids, return_inverse=True)
        merged = np.zeros((len(uniq), table.dim), np.float32)
        np.add.at(merged, inv, grad)
        if self.sync:
            with self._lock:
                self._pending_sparse.setdefault(name, []).append(
                    (uniq, merged / self.num_trainers)
                )
                if lr is not None:
                    self._pending_lr = float(lr)
        else:
            table.apply(uniq, merged, self.optimizer,
                        lr if lr is not None else self.lr, self.opt_attrs)

    def _flush_pending_sparse_locked(self):
        lr = getattr(self, "_pending_lr", None)
        lr = self.lr if lr is None else lr  # lr == 0.0 is legitimate
        self._pending_lr = None  # one step's lr never leaks into the next
        for name, pushes in self._pending_sparse.items():
            table = self.tables[name]
            all_ids = np.concatenate([i for i, _ in pushes])
            all_grads = np.concatenate([g for _, g in pushes])
            uniq, inv = np.unique(all_ids, return_inverse=True)
            merged = np.zeros((len(uniq), table.dim), np.float32)
            np.add.at(merged, inv, all_grads)
            table.apply(uniq, merged, self.optimizer, lr, self.opt_attrs)
        self._pending_sparse.clear()

    def do_pull_sparse(self, p):
        return {"value": self.tables[p["name"]].lookup(p["ids"].ravel())}

    def do_write_sparse(self, p):
        """Assign rows directly (reference lookup_sparse_table_write_op):
        unlike push, no optimizer update — the values ARE the new rows.
        LAST duplicate wins (both table implementations enforce it)."""
        self.tables[p["name"]].write(p["ids"], p["value"])

    def do_barrier(self, p):
        """All-trainer rendezvous (reference send_barrier/fetch_barrier).
        The last arrival flushes the step's accumulated sparse grads, so
        post-barrier pulls see exactly one optimizer step per row."""
        with self._lock:
            gen = self._barrier_gen
            self._barrier_count += 1
            if self._barrier_count >= self.num_trainers:
                if self.sync:
                    self._flush_pending_sparse_locked()
                self._barrier_count = 0
                self._barrier_gen += 1
                self._lock.notify_all()
            else:
                while self._barrier_gen == gen and not self._stopped.is_set():
                    self._lock.wait(timeout=1.0)

    def do_metric_push(self, p):
        """Global-metric reduction slot (fleet/metrics/metric.py): trainers
        push local counters; the slot reduces with `op`; a paired barrier
        makes the value step-consistent; metric_pull reads and the LAST
        reader resets for the next round."""
        import numpy as _np

        with self._lock:
            if not hasattr(self, "_metrics"):
                self._metrics = {}
            name, op = p["name"], p.get("op", "sum")
            val = _np.asarray(p["value"], _np.float64)
            slot = self._metrics.get(name)
            if slot is None:
                self._metrics[name] = {"value": val.copy(), "reads": 0,
                                       "n": int(p.get("num_trainers", 1))}
            else:
                if op == "sum":
                    slot["value"] = slot["value"] + val
                elif op == "max":
                    slot["value"] = _np.maximum(slot["value"], val)
                elif op == "min":
                    slot["value"] = _np.minimum(slot["value"], val)

    def do_metric_pull(self, p):
        with self._lock:
            slot = self._metrics[p["name"]]
            out = slot["value"].copy()
            slot["reads"] += 1
            if slot["reads"] >= slot["n"]:
                del self._metrics[p["name"]]
        return {"value": out}

    def do_put_record(self, p):
        """Global-shuffle record queue (data_set.h:200): hold lines for
        their destination trainer until it takes them."""
        with self._lock:
            if not hasattr(self, "_record_q"):
                self._record_q = {}
            self._record_q.setdefault(int(p["trainer"]), []).extend(
                p["line"].split("\n"))

    def do_take_records(self, p):
        with self._lock:
            q = getattr(self, "_record_q", {})
            lines = q.pop(int(p["trainer"]), [])
        return {"lines": "\n".join(lines)}

    def _dead_trainers_locked(self, now: float, timeout: float):
        return [tid for tid, ts in self._heartbeats.items()
                if now - ts > timeout]

    def do_heartbeat(self, p):
        """Trainer liveness (heart_beat_monitor.h): record last-seen time;
        reply with trainers considered dead."""
        import time

        now = time.monotonic()
        timeout = float(p.get("timeout", 30.0))
        with self._lock:
            self._heartbeats[int(p["trainer_id"])] = now
            dead = self._dead_trainers_locked(now, timeout)
        return {"dead": np.asarray(dead, np.int64)}

    def do_heartbeat_clear(self, p):
        """Supervisor-side reset after killing+respawning a trainer: the
        stale timestamp must not re-flag the fresh worker while it is
        still importing/compiling (it re-registers on its first beat)."""
        with self._lock:
            self._heartbeats.pop(int(p["trainer_id"]), None)

    def do_heartbeat_status(self, p):
        """Query-only liveness view for SUPERVISORS (the launcher's
        respawn loop): the dead list WITHOUT registering the caller as a
        trainer — the consumer the r4 verdict flagged as missing."""
        import time

        timeout = float(p.get("timeout", 30.0))
        with self._lock:
            dead = self._dead_trainers_locked(time.monotonic(), timeout)
        return {"dead": np.asarray(dead, np.int64)}

    # -- checkpoint (checkpoint_notify_op.cc / recv_save_op.cc) ---------
    def do_save(self, p):
        path = p["path"]
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # deep-copy everything UNDER the locks: np.savez runs after they
        # release, and a concurrent push mutating live arrays would tear
        # the snapshot (mixed-step params/moments)
        blobs: Dict[str, np.ndarray] = {}
        with self._lock:
            for name, slot in self.dense.items():
                blobs[f"dense/{name}"] = slot.value.copy()
                for k, v in slot.state.items():
                    blobs[f"dense_state/{name}/{k}"] = np.array(v)
            for name, t in self.tables.items():
                with t.lock:
                    # bind once: native-table properties each materialize
                    # a fresh FFI copy (already exactly n rows)
                    n_rows = t.n
                    ids, data, m, v, steps = t.ids, t.data, t.m, t.v, t.t
                    blobs[f"table/{name}/ids"] = np.asarray(ids[:n_rows])
                    blobs[f"table/{name}/data"] = np.asarray(data[:n_rows])
                    blobs[f"table/{name}/seed"] = np.asarray(t.seed, np.int64)
                    if m is not None:
                        blobs[f"table/{name}/m"] = np.asarray(m[:n_rows])
                        blobs[f"table/{name}/v"] = np.asarray(v[:n_rows])
                        blobs[f"table/{name}/t"] = np.asarray(steps[:n_rows])
        np.savez(path, **blobs)
        if not path.endswith(".npz"):
            os.replace(path + ".npz", path)
        return {"saved": len(blobs)}

    def do_load(self, p):
        with np.load(p["path"], allow_pickle=False) as z:
            with self._lock:
                for key in z.files:
                    parts = key.split("/")
                    if parts[0] == "dense":
                        self.dense[parts[1]] = _DenseSlot(z[key])
                for key in z.files:
                    parts = key.split("/")
                    if parts[0] == "dense_state":
                        self.dense[parts[1]].state[parts[2]] = z[key]
                tables = {k.split("/")[1] for k in z.files if k.startswith("table/")}
                for name in tables:
                    data = z[f"table/{name}/data"]
                    seed = int(z[f"table/{name}/seed"]) if f"table/{name}/seed" in z.files else 0
                    ids = z[f"table/{name}/ids"]
                    has_adam = f"table/{name}/m" in z.files
                    # restore through the factory so the native data
                    # plane survives a checkpoint round trip
                    t = _new_table(data.shape[1], seed=seed)
                    if hasattr(t, "import_state"):
                        t.import_state(
                            ids, data,
                            m=z[f"table/{name}/m"] if has_adam else None,
                            v=z[f"table/{name}/v"] if has_adam else None,
                            t=z[f"table/{name}/t"] if has_adam else None)
                    else:
                        t._grow(max(len(data), 1))
                        t.n = len(data)
                        t.data[: t.n] = data
                        t.ids[: t.n] = ids
                        t.slot_of = {int(r): i for i, r in enumerate(ids)}
                        order = np.argsort(ids)
                        t._sorted_ids = ids[order]
                        t._sorted_slots = order.astype(np.int64)
                        if has_adam:
                            cap = len(t.data)
                            t.m = np.zeros((cap, t.dim), np.float32)
                            t.v = np.zeros((cap, t.dim), np.float32)
                            t.t = np.zeros(cap, np.int64)
                            t.m[: t.n] = z[f"table/{name}/m"]
                            t.v[: t.n] = z[f"table/{name}/v"]
                            t.t[: t.n] = z[f"table/{name}/t"]
                    self.tables[name] = t
        return {"loaded": 1}

    def do_state(self, p):
        with self._lock:
            return {
                "dense": ",".join(sorted(self.dense)),
                "tables": ",".join(sorted(self.tables)),
                "rows": sum(t.n for t in self.tables.values()),
            }

    def do_stop(self, p):
        self._stopped.set()
        with self._lock:
            self._lock.notify_all()

    # -- optimizers -----------------------------------------------------
    def _apply_dense(self, name: str, slot: _DenseSlot, grad: np.ndarray,
                     lr: Optional[float] = None):
        lr = self.lr if lr is None else float(lr)
        if self.optimizer == "sgd":
            slot.value -= lr * grad
        elif self.optimizer == "adam":
            st = slot.state
            if not st:
                st["m"] = np.zeros_like(slot.value)
                st["v"] = np.zeros_like(slot.value)
                st["t"] = np.zeros((), np.int64)
            b1 = self.opt_attrs.get("beta1", 0.9)
            b2 = self.opt_attrs.get("beta2", 0.999)
            eps = self.opt_attrs.get("epsilon", 1e-8)
            st["t"] = st["t"] + 1
            st["m"] = b1 * st["m"] + (1 - b1) * grad
            st["v"] = b2 * st["v"] + (1 - b2) * grad * grad
            mhat = st["m"] / (1 - b1 ** int(st["t"]))
            vhat = st["v"] / (1 - b2 ** int(st["t"]))
            slot.value -= lr * mhat / (np.sqrt(vhat) + eps)
        else:
            raise RuntimeError(f"pserver optimizer {self.optimizer!r} unsupported")


def start_server(endpoint: str, server: ParameterServer,
                 block: bool = False) -> Tuple[threading.Thread, Callable[[], None]]:
    """The listen_and_serv event loop (listen_and_serv_op.cc): accept
    connections, dispatch framed requests to the handlers until stopped.
    Returns (thread, shutdown) when block=False."""
    host, port = endpoint.rsplit(":", 1)
    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind((host, int(port)))
    lsock.listen(64)
    lsock.settimeout(0.5)

    def conn_loop(sock):
        with sock:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while not server._stopped.is_set():
                try:
                    method, payload, nbytes = recv_msg_sized(sock)
                except (ConnectionError, OSError):
                    return
                # caller trace context (rpc.py TRACE_KEY): handlers must
                # never see the reserved key; when tracing is on, the
                # handler runs inside a child span of the remote caller
                trace_hdr = payload.pop(TRACE_KEY, None)
                t0 = time.perf_counter()
                try:
                    sp = _profiler.span(f"rpc_handle/{method}",
                                        cat="rpc_server", remote=trace_hdr)
                    sp.begin()
                    try:
                        reply = server.handle(method, payload)
                    finally:
                        sp.end()
                    sent = send_msg(sock, "ok", reply)
                except Exception as e:  # surface handler errors to the peer
                    try:
                        sent = send_msg(
                            sock, "error",
                            {"message": f"{type(e).__name__}: {e}"})
                    except OSError:
                        return
                _M_SREQ.labels(method=method).inc()
                _M_SREQ_T.labels(method=method).observe(
                    time.perf_counter() - t0)
                _M_SIN.labels(method=method).inc(nbytes)
                _M_SOUT.labels(method=method).inc(sent)
                if method == "stop":
                    return

    def accept_loop():
        with lsock:
            while not server._stopped.is_set():
                try:
                    sock, _ = lsock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                threading.Thread(target=conn_loop, args=(sock,), daemon=True).start()

    if block:
        accept_loop()
        return None, lambda: None
    thread = threading.Thread(target=accept_loop, daemon=True)
    thread.start()

    def shutdown():
        server._stopped.set()
        try:
            lsock.close()
        except OSError:
            pass

    return thread, shutdown
