"""Trainer-side communicator: the process-global PS client.

Counterpart of the reference Communicator singleton
(operators/distributed/communicator.h:180,253 — Start/Stop/Send over
RpcCtxMaps, the async send queue + merge thread) and the send/recv op
runtimes (distributed_ops/send_op.cc, recv_op.cc). Differences by
design: gradient merge across microbatches happens on-device (XLA) or on
the server (sync accumulate), so the client is a thin sharding router —
dense params route whole to their placed server; sparse tables shard
rows id % num_servers across ALL servers (the reference slices dense
params into blocks too; whole-param granularity keeps the executor's
donation story simple and wide/deep-scale dense params are small next to
the embedding tables).
"""
from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

import numpy as np

from .rpc import PSClient


class Communicator:
    _instance: Optional["Communicator"] = None
    _lock = threading.Lock()

    def __init__(
        self,
        endpoints: Sequence[str],
        trainer_id: int,
        num_trainers: int,
        placement: Optional[Dict[str, str]] = None,
        sync: bool = True,
        lr_fn=None,
    ):
        self.endpoints = list(endpoints)
        self.trainer_id = trainer_id
        self.num_trainers = num_trainers
        self.placement = dict(placement or {})
        self.sync = sync
        # callable returning the CURRENT step's lr (the trainer-side lr
        # schedule); shipped with sparse pushes too, not just dense
        self.lr_fn = lr_fn
        self.clients = {ep: PSClient(ep) for ep in self.endpoints}
        # shard fan-out runs concurrently: step latency is max-of-shards,
        # not sum-of-shards (PSClient sockets are per-thread, so pool
        # workers each hold their own connections)
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, len(self.endpoints)),
            thread_name_prefix="ps-fanout",
        )

    def _fanout(self, jobs):
        """Run [(fn, args...)] concurrently, propagate the first error."""
        if len(jobs) == 1:
            fn, *args = jobs[0]
            return [fn(*args)]
        futs = [self._pool.submit(fn, *args) for fn, *args in jobs]
        return [f.result() for f in futs]

    # -- lifecycle (reference Communicator::InitInstance/Start/Stop) ----
    @classmethod
    def init(cls, *args, **kwargs) -> "Communicator":
        with cls._lock:
            # construct the calling class but register on the BASE attr:
            # `cls._instance = ...` on a subclass would shadow it and
            # Communicator.get()/stop() would miss the instance
            Communicator._instance = cls(*args, **kwargs)
            return Communicator._instance

    @classmethod
    def get(cls) -> "Communicator":
        if cls._instance is None:
            raise RuntimeError(
                "PS Communicator not initialized: call "
                "Communicator.init(endpoints, trainer_id, num_trainers, ...) "
                "or transpiler.init_communicator(scope) first"
            )
        return cls._instance

    @classmethod
    def stop(cls):
        with cls._lock:
            inst, cls._instance = cls._instance, None
        if inst is not None:
            inst._pool.shutdown(wait=False, cancel_futures=True)
            for c in inst.clients.values():
                c.close()

    def shutdown_servers(self):
        for c in self.clients.values():
            try:
                c.call("stop")
            except Exception:
                pass

    # -- dense ----------------------------------------------------------
    def _client_for(self, name: str) -> PSClient:
        ep = self.placement.get(name)
        if ep is None:
            raise KeyError(f"param {name!r} has no pserver placement")
        return self.clients[ep]

    def init_dense(self, name: str, value: np.ndarray):
        self._client_for(name).call("init_dense", name=name, value=np.asarray(value))

    def push_dense(self, name: str, grad: np.ndarray, lr: Optional[float] = None):
        payload = {"name": name, "grad": np.asarray(grad)}
        if lr is not None:
            payload["lr"] = float(lr)  # per-step lr (schedules live on trainers)
        self._client_for(name).call("push_dense", **payload)

    def push_geo(self, name: str, delta: np.ndarray) -> np.ndarray:
        """Geo mode: additive param delta; reply is the fresh global value."""
        return self._client_for(name).call(
            "push_geo", name=name, delta=np.asarray(delta)
        )["value"]

    def pull_dense(self, name: str) -> np.ndarray:
        return self._client_for(name).call("pull_dense", name=name)["value"]

    def heartbeat(self, timeout: float = 30.0):
        """Report liveness to every pserver; returns the union of trainer
        ids any server considers dead (heart_beat_monitor.h)."""
        dead = set()
        for ep in self.endpoints:
            rep = self.clients[ep].call(
                "heartbeat", trainer_id=self.trainer_id, timeout=timeout
            )
            dead.update(int(t) for t in np.asarray(rep["dead"]).ravel())
        return sorted(dead)

    def save_server_state(self, dirname: str):
        """checkpoint_notify semantics: every pserver snapshots its shard."""
        for i, ep in enumerate(self.endpoints):
            self.clients[ep].call(
                "save", path=f"{dirname}/pserver_{i}.npz"
            )

    def load_server_state(self, dirname: str):
        for i, ep in enumerate(self.endpoints):
            self.clients[ep].call(
                "load", path=f"{dirname}/pserver_{i}.npz"
            )

    # -- dataset global-shuffle record queues (data_set.h:200) ----------
    def put_record(self, dest_trainer: int, line: str):
        self.put_records(dest_trainer, [line])

    def put_records(self, dest_trainer: int, lines):
        ep = self.endpoints[dest_trainer % len(self.endpoints)]
        self.clients[ep].call("put_record", trainer=int(dest_trainer),
                              line="\n".join(lines))

    def take_records(self, trainer: int) -> list:
        ep = self.endpoints[trainer % len(self.endpoints)]
        blob = self.clients[ep].call("take_records", trainer=int(trainer))
        text = blob["lines"]
        return text.split("\n") if text else []

    def barrier_all(self):
        self._fanout([
            (self.clients[ep].call, "barrier") for ep in self.endpoints
        ])

    # -- sparse (rows sharded id % num_servers) -------------------------
    def init_table(self, name: str, dim: int, seed: int = 0):
        for i, ep in enumerate(self.endpoints):
            self.clients[ep].call(
                "init_table", name=name, dim=dim, seed=seed + 7919 * i
            )

    def pull_sparse(self, table: str, ids: np.ndarray, dim: int) -> np.ndarray:
        ids = np.asarray(ids).ravel().astype(np.int64)
        out = np.empty((ids.size, dim), np.float32)
        n = len(self.endpoints)
        shard = ids % n
        jobs, masks = [], []
        for i, ep in enumerate(self.endpoints):
            mask = shard == i
            if not mask.any():
                continue
            jobs.append((self._pull_shard, ep, table, ids[mask] // n))
            masks.append(mask)
        for mask, rows in zip(masks, self._fanout(jobs)):
            out[mask] = rows
        return out

    def _pull_shard(self, ep, table, shard_ids):
        return self.clients[ep].call("pull_sparse", name=table, ids=shard_ids)["value"]

    def push_sparse(self, table: str, ids: np.ndarray, grad: np.ndarray,
                    lr: Optional[float] = None):
        if lr is None and self.lr_fn is not None:
            lr = float(self.lr_fn())
        ids = np.asarray(ids).ravel().astype(np.int64)
        grad = np.asarray(grad, np.float32).reshape(ids.size, -1)
        n = len(self.endpoints)
        shard = ids % n
        jobs = []
        for i, ep in enumerate(self.endpoints):
            mask = shard == i
            if not mask.any():
                continue
            jobs.append((self._push_shard, ep, table, ids[mask] // n, grad[mask], lr))
        self._fanout(jobs)

    def _push_shard(self, ep, table, shard_ids, shard_grad, lr=None):
        payload = {"name": table, "ids": shard_ids, "grad": shard_grad}
        if lr is not None:
            payload["lr"] = float(lr)
        self.clients[ep].call("push_sparse", **payload)

    def write_sparse(self, table: str, ids: np.ndarray, values: np.ndarray):
        """Assign rows (no optimizer step) — lookup_sparse_table_write."""
        ids = np.asarray(ids).ravel().astype(np.int64)
        values = np.asarray(values, np.float32).reshape(ids.size, -1)
        n = len(self.endpoints)
        shard = ids % n
        jobs = []
        for i, ep in enumerate(self.endpoints):
            mask = shard == i
            if not mask.any():
                continue
            jobs.append((self._write_shard, ep, table, ids[mask] // n,
                         values[mask]))
        self._fanout(jobs)

    def _write_shard(self, ep, table, shard_ids, shard_vals):
        self.clients[ep].call("write_sparse", name=table, ids=shard_ids,
                              value=shard_vals)




class GeoCommunicator(Communicator):
    """Geo-async PS mode (reference communicator.h:396 GeoCommunicator +
    geo_sgd_transpiler.py): trainers run their LOCAL optimizer every step;
    every `k_steps`, each param's delta since the last sync is pushed
    additively and the fresh global value (sum of everyone's progress)
    replaces the local copy."""

    def __init__(self, *args, k_steps: int = 4, **kwargs):
        super().__init__(*args, **kwargs)
        self.k_steps = int(k_steps)
        self._snapshots: Dict[str, np.ndarray] = {}
        self._geo_step = 0

    def snapshot(self, params: Dict[str, np.ndarray]):
        self._snapshots = {n: np.array(v, np.float32) for n, v in params.items()}

    def maybe_sync(self, params: Dict[str, np.ndarray]):
        """Call once per local step with current param values. On sync
        steps, returns {name: fresh global value}; else None. The first
        call auto-snapshots (a zero snapshot would push the FULL initial
        params as a delta and every trainer would add its copy)."""
        if not self._snapshots:
            self.snapshot(params)
            return None
        self._geo_step += 1
        if self._geo_step % self.k_steps != 0:
            return None
        names = list(params)
        deltas = [np.asarray(params[n], np.float32) - self._snapshots[n]
                  for n in names]
        vals = self._fanout([
            (self.push_geo, n, d) for n, d in zip(names, deltas)
        ])
        fresh = dict(zip(names, vals))
        self._snapshots = {n: np.array(v, np.float32) for n, v in fresh.items()}
        return fresh
