"""Trainer-side communicator: the process-global PS client.

Counterpart of the reference Communicator singleton
(operators/distributed/communicator.h:180,253 — Start/Stop/Send over
RpcCtxMaps, the async send queue + merge thread) and the send/recv op
runtimes (distributed_ops/send_op.cc, recv_op.cc). Differences by
design: gradient merge across microbatches happens on-device (XLA) or on
the server (sync accumulate), so the client is a thin sharding router —
dense params route whole to their placed server; sparse tables shard
rows id % num_servers across ALL servers (the reference slices dense
params into blocks too; whole-param granularity keeps the executor's
donation story simple and wide/deep-scale dense params are small next to
the embedding tables).
"""
from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

import numpy as np

from .rpc import PSClient


class Communicator:
    _instance: Optional["Communicator"] = None
    _lock = threading.Lock()

    def __init__(
        self,
        endpoints: Sequence[str],
        trainer_id: int,
        num_trainers: int,
        placement: Optional[Dict[str, str]] = None,
        sync: bool = True,
    ):
        self.endpoints = list(endpoints)
        self.trainer_id = trainer_id
        self.num_trainers = num_trainers
        self.placement = dict(placement or {})
        self.sync = sync
        self.clients = {ep: PSClient(ep) for ep in self.endpoints}
        # shard fan-out runs concurrently: step latency is max-of-shards,
        # not sum-of-shards (PSClient sockets are per-thread, so pool
        # workers each hold their own connections)
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, len(self.endpoints)),
            thread_name_prefix="ps-fanout",
        )

    def _fanout(self, jobs):
        """Run [(fn, args...)] concurrently, propagate the first error."""
        if len(jobs) == 1:
            fn, *args = jobs[0]
            return [fn(*args)]
        futs = [self._pool.submit(fn, *args) for fn, *args in jobs]
        return [f.result() for f in futs]

    # -- lifecycle (reference Communicator::InitInstance/Start/Stop) ----
    @classmethod
    def init(cls, *args, **kwargs) -> "Communicator":
        with cls._lock:
            cls._instance = Communicator(*args, **kwargs)
            return cls._instance

    @classmethod
    def get(cls) -> "Communicator":
        if cls._instance is None:
            raise RuntimeError(
                "PS Communicator not initialized: call "
                "Communicator.init(endpoints, trainer_id, num_trainers, ...) "
                "or transpiler.init_communicator(scope) first"
            )
        return cls._instance

    @classmethod
    def stop(cls):
        with cls._lock:
            inst, cls._instance = cls._instance, None
        if inst is not None:
            inst._pool.shutdown(wait=False, cancel_futures=True)
            for c in inst.clients.values():
                c.close()

    def shutdown_servers(self):
        for c in self.clients.values():
            try:
                c.call("stop")
            except Exception:
                pass

    # -- dense ----------------------------------------------------------
    def _client_for(self, name: str) -> PSClient:
        ep = self.placement.get(name)
        if ep is None:
            raise KeyError(f"param {name!r} has no pserver placement")
        return self.clients[ep]

    def init_dense(self, name: str, value: np.ndarray):
        self._client_for(name).call("init_dense", name=name, value=np.asarray(value))

    def push_dense(self, name: str, grad: np.ndarray):
        self._client_for(name).call("push_dense", name=name, grad=np.asarray(grad))

    def pull_dense(self, name: str) -> np.ndarray:
        return self._client_for(name).call("pull_dense", name=name)["value"]

    def barrier_all(self):
        self._fanout([
            (self.clients[ep].call, "barrier") for ep in self.endpoints
        ])

    # -- sparse (rows sharded id % num_servers) -------------------------
    def init_table(self, name: str, dim: int, seed: int = 0):
        for i, ep in enumerate(self.endpoints):
            self.clients[ep].call(
                "init_table", name=name, dim=dim, seed=seed + 7919 * i
            )

    def pull_sparse(self, table: str, ids: np.ndarray, dim: int) -> np.ndarray:
        ids = np.asarray(ids).ravel().astype(np.int64)
        out = np.empty((ids.size, dim), np.float32)
        n = len(self.endpoints)
        shard = ids % n
        jobs, masks = [], []
        for i, ep in enumerate(self.endpoints):
            mask = shard == i
            if not mask.any():
                continue
            jobs.append((self._pull_shard, ep, table, ids[mask] // n))
            masks.append(mask)
        for mask, rows in zip(masks, self._fanout(jobs)):
            out[mask] = rows
        return out

    def _pull_shard(self, ep, table, shard_ids):
        return self.clients[ep].call("pull_sparse", name=table, ids=shard_ids)["value"]

    def push_sparse(self, table: str, ids: np.ndarray, grad: np.ndarray):
        ids = np.asarray(ids).ravel().astype(np.int64)
        grad = np.asarray(grad, np.float32).reshape(ids.size, -1)
        n = len(self.endpoints)
        shard = ids % n
        jobs = []
        for i, ep in enumerate(self.endpoints):
            mask = shard == i
            if not mask.any():
                continue
            jobs.append((self._push_shard, ep, table, ids[mask] // n, grad[mask]))
        self._fanout(jobs)

    def _push_shard(self, ep, table, shard_ids, shard_grad):
        self.clients[ep].call("push_sparse", name=table, ids=shard_ids, grad=shard_grad)


