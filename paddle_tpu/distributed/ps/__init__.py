"""Parameter-server (sparse/dense distributed) training.

TPU-native re-engineering of the reference PS stack
(/root/reference/paddle/fluid/operators/distributed/communicator.h:180,
operators/distributed_ops/listen_and_serv_op.cc,
transpiler/distribute_transpiler.py:256,
operators/distributed/large_scale_kv.h,
operators/distributed_ops/distributed_lookup_table_op.cc).

Architecture: the device-side training step stays ONE jitted XLA program
(the executor's compile-and-cache path is untouched); parameter-server
traffic crosses the host boundary through `jax.experimental.io_callback`
ops embedded in the program — `send` pushes gradients, `recv` pulls fresh
parameters, `distributed_lookup_table` prefetches sparse embedding rows.
The server itself is host-side Python over a length-prefixed TCP
protocol (the reference's gRPC/BRPC SendRecvService role), holding dense
parameter blocks and sparse row tables, applying its own optimizer on
received gradients (sync: barrier-accumulate across trainers; async:
apply-on-arrival, the reference AsyncCommunicator semantics).
"""
from .rpc import PSClient, serialize, deserialize
from .server import ParameterServer, start_server
from .communicator import Communicator
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig

__all__ = [
    "PSClient",
    "ParameterServer",
    "start_server",
    "Communicator",
    "DistributeTranspiler",
    "DistributeTranspilerConfig",
    "serialize",
    "deserialize",
]
