"""Framed-TCP RPC for parameter-server traffic.

The reference routes PS traffic through gRPC/BRPC service stubs
(operators/distributed/grpc/, sendrecvop_utils.cc). Here the wire format
is a 4-byte big-endian length prefix + a compact binary message: method
string, then a payload dict whose numpy arrays are encoded raw
(dtype/shape header + buffer) — no pickle on the hot path, so a malicious
peer can at worst corrupt tensors, not execute code.
"""
from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from ... import monitor as _monitor
from ... import profiler as _profiler

_U32 = struct.Struct(">I")

# reserved payload key carrying the caller's "trace_id:span_id" context;
# the server pops it before dispatching to a handler
TRACE_KEY = "__trace__"

# client-side RPC telemetry: request count + latency + wire bytes per
# method — count/sum over a window give the absolute msgs/s and MB/s
# numbers the PS throughput story was missing
_M_REQ = _monitor.counter(
    "ps_client_requests_total", "PS RPC requests issued", ("method",))
_M_REQ_T = _monitor.histogram(
    "ps_client_request_seconds", "PS RPC round-trip latency", ("method",))
_M_TX = _monitor.counter(
    "ps_client_bytes_sent_total", "PS RPC request bytes on the wire",
    ("method",))
_M_RX = _monitor.counter(
    "ps_client_bytes_recv_total", "PS RPC reply bytes on the wire",
    ("method",))

# payload value tags
_T_ARR, _T_STR, _T_INT, _T_FLT, _T_BYTES, _T_NONE = b"A", b"S", b"I", b"F", b"B", b"N"


def serialize(method: str, payload: Dict[str, Any]) -> bytes:
    parts = [_U32.pack(len(method)), method.encode()]
    parts.append(_U32.pack(len(payload)))
    for key, val in payload.items():
        kb = key.encode()
        parts += [_U32.pack(len(kb)), kb]
        if isinstance(val, np.ndarray):
            dt = np.dtype(val.dtype).str.encode()
            shape = np.asarray(val.shape, np.int64).tobytes()
            buf = np.ascontiguousarray(val).tobytes()
            parts += [
                _T_ARR, _U32.pack(len(dt)), dt,
                _U32.pack(val.ndim), shape, _U32.pack(len(buf)), buf,
            ]
        elif isinstance(val, str):
            vb = val.encode()
            parts += [_T_STR, _U32.pack(len(vb)), vb]
        elif isinstance(val, bool) or isinstance(val, (int, np.integer)):
            parts += [_T_INT, struct.pack(">q", int(val))]
        elif isinstance(val, (float, np.floating)):
            parts += [_T_FLT, struct.pack(">d", float(val))]
        elif isinstance(val, (bytes, bytearray)):
            parts += [_T_BYTES, _U32.pack(len(val)), bytes(val)]
        elif val is None:
            parts += [_T_NONE]
        else:
            raise TypeError(f"unsupported RPC value type {type(val)} for {key!r}")
    return b"".join(parts)


def deserialize(data: bytes):
    off = 0

    def take(n):
        nonlocal off
        chunk = data[off:off + n]
        off += n
        return chunk

    def take_u32():
        return _U32.unpack(take(4))[0]

    method = take(take_u32()).decode()
    n = take_u32()
    payload: Dict[str, Any] = {}
    for _ in range(n):
        key = take(take_u32()).decode()
        tag = take(1)
        if tag == _T_ARR:
            dt = np.dtype(take(take_u32()).decode())
            ndim = take_u32()
            shape = tuple(np.frombuffer(take(8 * ndim), np.int64).tolist())
            buf = take(take_u32())
            payload[key] = np.frombuffer(buf, dt).reshape(shape).copy()
        elif tag == _T_STR:
            payload[key] = take(take_u32()).decode()
        elif tag == _T_INT:
            payload[key] = struct.unpack(">q", take(8))[0]
        elif tag == _T_FLT:
            payload[key] = struct.unpack(">d", take(8))[0]
        elif tag == _T_BYTES:
            payload[key] = take(take_u32())
        elif tag == _T_NONE:
            payload[key] = None
        else:
            raise ValueError(f"bad RPC tag {tag!r}")
    return method, payload


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def send_msg(sock: socket.socket, method: str, payload: Dict[str, Any]) -> int:
    """Send one framed message; returns the wire byte count."""
    body = serialize(method, payload)
    sock.sendall(_U32.pack(len(body)) + body)
    return len(body) + 4


def recv_msg_sized(sock: socket.socket):
    """(method, payload, wire_bytes) — the sized variant instrumentation
    uses; recv_msg keeps the 2-tuple contract."""
    (n,) = _U32.unpack(_recv_exact(sock, 4))
    method, payload = deserialize(_recv_exact(sock, n))
    return method, payload, n + 4


def recv_msg(sock: socket.socket):
    method, payload, _ = recv_msg_sized(sock)
    return method, payload


class PSClient:
    """One persistent connection per (thread, endpoint) — the reference
    keeps gRPC channels per endpoint (grpc_client.h GetChannel)."""

    def __init__(self, endpoint: str, timeout: float = 120.0,
                 recv_timeout: Optional[float] = None):
        """recv_timeout: bound on each reply (None = wait forever, the
        trainer default — barrier replies legitimately block). The
        launcher's heartbeat supervisor sets it so its liveness never
        depends on a hung pserver."""
        host, port = endpoint.rsplit(":", 1)
        self.addr = (host, int(port))
        self.timeout = timeout
        self.recv_timeout = recv_timeout
        self._local = threading.local()
        # every per-thread socket, so close() can release connections opened
        # by pool workers, not just the calling thread's
        self._all_socks = set()
        self._all_lock = threading.Lock()

    def _sock(self) -> socket.socket:
        sock = getattr(self._local, "sock", None)
        if sock is not None and sock.fileno() == -1:
            # close() (possibly from another thread) invalidated it
            sock = None
        if sock is None:
            # retry the first connect: trainers race pserver startup
            # (the reference grpc client does the same via channel waits)
            import time

            # supervisors (recv_timeout set) must not blocked-retry for
            # the trainer-grade 30s window on a dead endpoint
            retry_window = (self.recv_timeout
                            if self.recv_timeout is not None else 30.0)
            deadline = time.monotonic() + retry_window
            while True:
                try:
                    sock = socket.create_connection(self.addr, timeout=self.timeout)
                    break
                except OSError:
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.2)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # no recv deadline: barrier replies legitimately block until
            # every trainer arrives (stragglers must not kill the job —
            # the reference grpc client uses effectively-infinite
            # deadlines for the same reason)
            sock.settimeout(self.recv_timeout)
            self._local.sock = sock
            with self._all_lock:
                self._all_socks.add(sock)
        return sock

    def call(self, method: str, **payload):
        # chaos site: an armed rpc_error kills the call before any bytes
        # move — the dead-pserver shape (typed errors.Unavailable)
        from ... import chaos as _chaos

        _chaos.rpc_error(method)
        sock = self._sock()
        # the RPC span is the remote parent: its trace context rides in
        # the payload, so the server's handler span parents onto it and
        # one logical push/pull renders as a connected cross-rank flow
        sp = _profiler.span(f"rpc/{method}", cat="rpc_client")
        sp.begin()
        t0 = time.perf_counter()
        try:
            hdr = _profiler.remote_context(sp)
            if hdr is not None:
                payload[TRACE_KEY] = hdr
            sent = send_msg(sock, method, payload)
            rmethod, rpayload, recvd = recv_msg_sized(sock)
        except (ConnectionError, OSError):
            self.close()
            raise
        finally:
            sp.end()
        _M_REQ.labels(method=method).inc()
        _M_REQ_T.labels(method=method).observe(time.perf_counter() - t0)
        _M_TX.labels(method=method).inc(sent)
        _M_RX.labels(method=method).inc(recvd)
        if rmethod == "error":
            raise RuntimeError(f"pserver {self.addr}: {rpayload.get('message')}")
        return rpayload

    def close(self):
        """Close ALL connections this client ever opened (any thread)."""
        with self._all_lock:
            socks, self._all_socks = self._all_socks, set()
        for sock in socks:
            try:
                sock.close()
            except OSError:
                pass
        self._local.sock = None
