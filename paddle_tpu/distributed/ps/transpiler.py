"""DistributeTranspiler: split a training program into trainer + pserver
halves.

Counterpart of /root/reference/python/paddle/fluid/transpiler/
distribute_transpiler.py:256 (`transpile(trainer_id, program, pservers,
trainers, sync_mode)`), re-engineered for the one-XLA-program executor:

- trainer program: optimizer ops are REMOVED and replaced with a tail of
  `send` (push grads + sync barrier) and `recv` (pull updated params)
  ops — both lower to ordered io_callbacks inside the jitted step.
- pserver side: instead of a generated sub-program interpreted by
  listen_and_serv (the reference's design), `get_pserver(endpoint)`
  returns a configured ParameterServer whose optimizer/lr replicate the
  removed optimizer ops. Whole-param placement round-robins params over
  pservers by size (the reference additionally block-slices large dense
  params; embedding scale lives in the sparse tables here, which shard
  by row over ALL pservers).

The lr feed is kept and its current value ships with every dense and
sparse push (the reference ships the lr var to the pserver program), so
lr schedules keep working across the PS boundary.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

_OPT_TYPES = {
    "sgd", "momentum", "adam", "adamw", "lamb", "lars_momentum",
    "adagrad", "rmsprop", "adamax", "adadelta", "ftrl",
}
_SERVER_SUPPORTED = {"sgd", "adam"}


@dataclass
class DistributeTranspilerConfig:
    sync_mode: bool = True
    # reference knobs accepted for API parity (slice_var_up etc. are
    # no-ops at whole-param granularity)
    slice_var_up: bool = True
    min_block_size: int = 8192


class DistributeTranspiler:
    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._program = None
        self._placement: Dict[str, str] = {}
        self._endpoints: List[str] = []
        self._trainer_id = 0
        self._trainers = 1
        self._optimizer = "sgd"
        self._lr = 0.01
        self._opt_attrs: Dict[str, float] = {}
        self._param_shapes: Dict[str, Tuple[int, ...]] = {}
        self._tables: Dict[str, int] = {}

    # -- the reference entry point -------------------------------------
    def transpile(self, trainer_id: int, program=None, pservers: str = "",
                  trainers: int = 1, sync_mode: bool = True,
                  startup_program=None):
        from ...framework.program import default_main_program

        program = program or default_main_program()
        self._program = program
        self._trainer_id = trainer_id
        self._trainers = trainers
        self.config.sync_mode = sync_mode
        self._endpoints = [e.strip() for e in pservers.split(",") if e.strip()]
        if not self._endpoints:
            raise ValueError("transpile needs at least one pserver endpoint")

        block = program.global_block()

        # 1. harvest the optimizer ops: (param, grad) pairs + update rule
        opt_idx = [i for i, op in enumerate(block.ops) if op.type in _OPT_TYPES]
        if not opt_idx:
            raise ValueError("no optimizer ops found; run minimize() first")
        params_grads: List[Tuple[str, str]] = []
        lr_names = set()
        for i in opt_idx:
            op = block.ops[i]
            self._optimizer = op.type
            a = op.all_attrs()
            self._opt_attrs = {
                k: a[k] for k in ("beta1", "beta2", "epsilon", "mu") if k in a
            }
            pv = {x.parameter: list(x.arguments) for x in op.desc.inputs}
            params_grads.append((pv["Param"][0], pv["Grad"][0]))
            if "LearningRate" in pv:
                lr_names.add(pv["LearningRate"][0])
        if self._optimizer not in _SERVER_SUPPORTED:
            raise NotImplementedError(
                f"pserver-side optimizer {self._optimizer!r}; supported: "
                f"{sorted(_SERVER_SUPPORTED)}"
            )
        extra = getattr(program, "_extra_feeds", {})
        self._lr_names = set(lr_names)
        for n in lr_names:
            if n in extra:
                self._lr = float(extra[n]())

        # 2. placement: params round-robin over endpoints, largest first
        #    (reference RoundRobin block placement)
        sized = []
        for pname, _ in params_grads:
            var = block._find_var_recursive(pname)
            shape = tuple(int(d) for d in var.shape)
            self._param_shapes[pname] = shape
            sized.append((int(np.prod(shape)), pname))
        loads = {ep: 0 for ep in self._endpoints}
        for size, pname in sorted(sized, reverse=True):
            ep = min(self._endpoints, key=lambda e: loads[e])
            self._placement[pname] = ep
            loads[ep] += size

        # 3. sparse tables: every distributed_lookup_table in the program
        for op in block.ops:
            if op.type == "distributed_lookup_table":
                a = op.all_attrs()
                self._tables[a["table_name"]] = int(a["dim"])

        # 4. surgery: drop optimizer ops (+ their accumulator-only
        #    bookkeeping is server-side now), append send + recv. The lr
        #    feed is KEPT and shipped with every push, so lr schedules
        #    keep working (the reference ships the lr var to the pserver
        #    program; previously frozen at transpile time here)
        for i in reversed(opt_idx):
            block._remove_op(i)

        grad_vars = [
            block._find_var_recursive(g) for _, g in params_grads
        ]
        param_names = [p for p, _ in params_grads]
        from ...framework import unique_name

        lr_vars = [
            block._find_var_recursive(n) for n in sorted(lr_names)
            if block._find_var_recursive(n) is not None
        ]
        token = block.create_var(
            name=unique_name.generate("@PS_SEND_TOKEN"), shape=[],
            dtype="float32", stop_gradient=True,
        )
        block.append_op(
            "send",
            inputs={"X": grad_vars, "LearningRate": lr_vars[:1]},
            outputs={"Out": [token]},
            attrs={
                "send_varnames": param_names,
                "sync_mode": self.config.sync_mode,
            },
        )
        shapes_flat: List[int] = []
        param_vars = []
        for p in param_names:
            var = block._find_var_recursive(p)
            param_vars.append(var)
            shape = self._param_shapes[p]
            shapes_flat += [len(shape), *shape]
        block.append_op(
            "recv",
            inputs={"X": [token]},
            outputs={"Out": param_vars},
            attrs={"recv_varnames": param_names, "recv_shapes": shapes_flat},
        )
        return self

    # -- artifacts ------------------------------------------------------
    def get_trainer_program(self):
        return self._program

    def get_pserver(self, endpoint: str):
        """Configured server for `endpoint` (the reference returns a
        pserver Program to interpret; here the optimizer runs native)."""
        from .server import ParameterServer

        return ParameterServer(
            num_trainers=self._trainers,
            sync=self.config.sync_mode,
            optimizer=self._optimizer,
            lr=self._lr,
            optimizer_attrs=self._opt_attrs,
        )

    def get_pserver_programs(self, endpoint: str):
        return self.get_pserver(endpoint), None  # (main, startup) parity shim

    def init_communicator(self, scope):
        """Trainer-side bring-up: connect, register tables, seed params
        (trainer 0's initial values win — reference init_from_pserver
        after trainer 0 pushes), then pull so every trainer starts
        identical."""
        from .communicator import Communicator

        # the EXACT lr var names harvested from the optimizer ops in
        # transpile() — name heuristics don't survive unique_name suffixes
        lr_fn = None
        extra = getattr(self._program, "_extra_feeds", {}) if self._program else {}
        for n in getattr(self, "_lr_names", ()):
            if n in extra:
                lr_fn = extra[n]
                break
        comm = Communicator.init(
            self._endpoints, self._trainer_id, self._trainers,
            placement=self._placement, sync=self.config.sync_mode,
            lr_fn=lr_fn,
        )
        for name, dim in self._tables.items():
            comm.init_table(name, dim)
        if self._trainer_id == 0:
            for name in self._placement:
                comm.init_dense(name, np.asarray(scope.get(name), np.float32))
        comm.barrier_all()
        for name in self._placement:
            scope.set(name, np.asarray(comm.pull_dense(name)))
        comm.barrier_all()
        return comm
