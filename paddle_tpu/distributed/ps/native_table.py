"""ctypes binding to the C++ sparse-table data plane (csrc/ps_table.cc).

The reference's PS data plane is C++ (operators/distributed/
large_scale_kv.h rows served by the brpc service); the round-4 verdict
flagged the TPU build's numpy tables as the remaining Python tier. This
binding swaps the row operations (first-touch init, bulk lookup,
vectorized SGD/Adam apply, assignment writes) for the native
implementation while keeping the SAME deterministic init and npz
checkpoint format, so native and Python tables are interchangeable
mid-job. Falls back silently when the .so is absent (build:
`make -C csrc ps`)."""
from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

_LIB = None
_TRIED = False


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "lib", "libpaddle_tpu_ps.so",
    )
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.pt_table_new.restype = ctypes.c_void_p
    lib.pt_table_new.argtypes = [ctypes.c_int64, ctypes.c_int64]
    lib.pt_table_free.argtypes = [ctypes.c_void_p]
    lib.pt_table_rows.restype = ctypes.c_int64
    lib.pt_table_rows.argtypes = [ctypes.c_void_p]
    lib.pt_table_lookup.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
    lib.pt_table_write.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
    lib.pt_table_apply.restype = ctypes.c_int
    lib.pt_table_apply.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        ctypes.c_float]
    lib.pt_table_export_ids.restype = ctypes.c_int64
    lib.pt_table_export_ids.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
    lib.pt_table_import_adam.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p]
    lib.pt_table_data_ptr.restype = ctypes.c_void_p
    lib.pt_table_data_ptr.argtypes = [ctypes.c_void_p]
    lib.pt_table_m_ptr.restype = ctypes.c_void_p
    lib.pt_table_m_ptr.argtypes = [ctypes.c_void_p]
    lib.pt_table_v_ptr.restype = ctypes.c_void_p
    lib.pt_table_v_ptr.argtypes = [ctypes.c_void_p]
    lib.pt_table_t_ptr.restype = ctypes.c_void_p
    lib.pt_table_t_ptr.argtypes = [ctypes.c_void_p]
    _LIB = lib
    return lib


def available() -> bool:
    return _load() is not None and os.environ.get(
        "PADDLE_TPU_NATIVE_PS", "1") != "0"


class NativeSparseTable:
    """Drop-in for server._SparseTable over the C++ row block: same
    lock discipline, same init hash, same save-path attribute surface
    (ids/data/m/v/t slices)."""

    def __init__(self, dim: int, seed: int = 0, capacity: int = 1024):
        self.dim = int(dim)
        self.seed = int(seed)
        self.lock = threading.RLock()
        self._h = _load().pt_table_new(self.dim, self.seed)

    def __del__(self):
        lib = _LIB
        if lib is not None and getattr(self, "_h", None):
            lib.pt_table_free(self._h)
            self._h = None

    # -- hot path -------------------------------------------------------
    @property
    def n(self) -> int:
        return int(_LIB.pt_table_rows(self._h))

    def lookup(self, ids: np.ndarray) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        out = np.empty((ids.size, self.dim), np.float32)
        with self.lock:
            _LIB.pt_table_lookup(self._h, ids.ctypes.data, ids.size,
                                 out.ctypes.data)
        return out

    def write(self, ids: np.ndarray, values: np.ndarray) -> None:
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        values = np.ascontiguousarray(values, np.float32).reshape(
            ids.size, self.dim)
        with self.lock:
            _LIB.pt_table_write(self._h, ids.ctypes.data, ids.size,
                                values.ctypes.data)

    def apply(self, uniq_ids, grads, optimizer, lr, attrs):
        uniq_ids = np.ascontiguousarray(uniq_ids, np.int64).ravel()
        grads = np.ascontiguousarray(grads, np.float32)
        opt = {"sgd": 0, "adam": 1}.get(optimizer)
        if opt is None:
            raise RuntimeError(f"pserver optimizer {optimizer!r} unsupported")
        with self.lock:
            rc = _LIB.pt_table_apply(
                self._h, uniq_ids.ctypes.data, uniq_ids.size,
                grads.ctypes.data, opt, float(lr),
                float(attrs.get("beta1", 0.9)),
                float(attrs.get("beta2", 0.999)),
                float(attrs.get("epsilon", 1e-8)))
        if rc != 0:
            raise RuntimeError(f"native ps apply failed (rc={rc})")

    # -- checkpoint surface (server.do_save slices these) ---------------
    @property
    def ids(self) -> np.ndarray:
        n = self.n
        if n == 0:
            return np.zeros(0, np.int64)
        out = np.empty(n, np.int64)
        _LIB.pt_table_export_ids(self._h, out.ctypes.data, out.size)
        return out

    def import_state(self, ids, data, m=None, v=None, t=None) -> None:
        """Checkpoint restore (server.do_load): bulk-assign rows and,
        when present, the Adam state."""
        self.write(ids, data)
        if m is not None:
            ids = np.ascontiguousarray(ids, np.int64).ravel()
            m = np.ascontiguousarray(m, np.float32).reshape(ids.size, self.dim)
            v = np.ascontiguousarray(v, np.float32).reshape(ids.size, self.dim)
            t = np.ascontiguousarray(t, np.int64).ravel()
            with self.lock:
                _LIB.pt_table_import_adam(
                    self._h, ids.ctypes.data, ids.size, m.ctypes.data,
                    v.ctypes.data, t.ctypes.data)

    def _block(self, ptr_fn, dtype, cols) -> Optional[np.ndarray]:
        ptr = ptr_fn(self._h)
        if not ptr:
            return None
        n = self.n
        buf = (ctypes.c_char * (n * cols * np.dtype(dtype).itemsize)
               ).from_address(ptr)
        return np.frombuffer(buf, dtype).reshape(n, cols).copy()

    @property
    def data(self) -> np.ndarray:
        out = self._block(_LIB.pt_table_data_ptr, np.float32, self.dim)
        if out is None:  # empty table: vector::data() is null at n == 0
            return np.zeros((0, self.dim), np.float32)
        return out

    @property
    def m(self):
        return self._block(_LIB.pt_table_m_ptr, np.float32, self.dim)

    @property
    def v(self):
        return self._block(_LIB.pt_table_v_ptr, np.float32, self.dim)

    @property
    def t(self):
        b = self._block(_LIB.pt_table_t_ptr, np.int64, 1)
        return None if b is None else b.reshape(-1)
