"""paddle.distributed equivalent.

Counterpart of /root/reference/python/paddle/distributed/ (collective API
collective.py:59-419, dygraph parallel.py:32, fleet/, launch.py). The
communication backend is the JAX distributed runtime + XLA collectives over
ICI/DCN instead of NCCL/gloo/gRPC (SURVEY.md §5.8).
"""
from ..parallel.env import (  # noqa: F401
    ParallelEnv,
    get_rank,
    get_world_size,
    init_parallel_env,
)
from . import fleet  # noqa: F401
from .collective import (  # noqa: F401
    ReduceOp,
    all_gather,
    all_reduce,
    barrier,
    broadcast,
    reduce,
    scatter,
)
from .parallel import DataParallel  # noqa: F401


def spawn(func, args=(), nprocs=-1, **kwargs):
    """Reference distributed/spawn.py. On TPU hosts one process owns all
    local chips, so in-host parallelism is mesh sharding, not process
    spawning; multi-host jobs use `python -m paddle_tpu.distributed.launch`."""
    import multiprocessing as mp
    import os

    if nprocs in (-1, 0, 1):
        func(*args)
        return
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nprocs),
        }

        def target(rank=rank, env=env):
            os.environ.update(env)
            func(*args)

        p = ctx.Process(target=target)
        p.start()
        procs.append(p)
    for p in procs:
        p.join()
        if p.exitcode != 0:
            raise RuntimeError(f"spawned trainer exited with {p.exitcode}")
