"""Data-parallel gradient communication: buckets, overlap, quantization.

The naive DP sync fires one blocking fp32 all-reduce per parameter after
the whole backward finishes — every collective pays full dispatch latency
and none of it overlaps compute. This layer replaces that loop on both
DP paths (dygraph ``DataParallel`` and the static/Fleet recipe) with the
scheme EQuARX (arXiv:2506.17615) and the DDP literature converge on:

- **Bucketing**: gradients coalesce into fixed-size byte buckets
  (``PADDLE_TPU_DP_BUCKET_MB``, default 25MB) assigned in REVERSE
  parameter-build order — backward produces grads roughly output-to-input,
  so reverse order makes buckets fill early. Each bucket flattens into one
  fp32 buffer and ships as ONE collective.
- **Backward overlap**: the dygraph tracer notifies a grad-ready hook as
  each gradient's last producing op executes; a bucket dispatches the
  moment its last grad lands, on a dedicated comms thread, so the
  collective runs while the remaining backward still executes. The host
  blocks only in :meth:`GradBucketer.sync` — the blocking remainder is
  what the goodput ``collective`` bucket records.
- **Quantized mode** (``PADDLE_TPU_DP_QUANTIZE=int8``): blockwise int8
  with per-block fp32 scales cuts wire bytes ~4x; an error-feedback
  residual per bucket (the compensation buffer of 1-bit-Adam/EF-SGD
  lineage) carries this step's quantization error into the next step's
  payload so the training trajectory matches exact-sum within noise. The
  residuals persist with optimizer state (``residual_state`` /
  ``load_residual_state``) so restarts don't lose the compensation.

Byte accounting is wire-honest: the ``collective_bytes_total`` counter
records the bytes actually shipped (int8 payload + scales in quantized
mode), and ``collective_logical_bytes_total`` the fp32-equivalent, so the
quantized-vs-exact ratio is auditable from any metrics snapshot
(tools/obs_report.py renders it as the ``comms`` section).

Bucket assignment MUST be identical on every rank — a divergent layout
silently corrupts training (rank A averages its attention weights against
rank B's MLP weights). Assignment is therefore a pure function of the
parameter (name, shape, dtype) sequence, and the first cross-process sync
verifies a layout digest across ranks before any payload moves.
"""
from __future__ import annotations

import concurrent.futures
import functools
import hashlib
import threading
import time
import weakref
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import chaos as _chaos
from .. import flags as _flags
from .. import goodput as _goodput
from .. import profiler as _profiler

__all__ = [
    "DEFAULT_BLOCK", "BucketSlot", "Bucket", "assign_buckets",
    "layout_signature", "quantize_blockwise", "dequantize_blockwise",
    "GradBucketer", "ProcessTransport", "LoopbackTransport",
    "bucket_mb", "overlap_enabled", "quantize_mode",
    "wire_nbytes", "predicted_step_bytes",
    "residual_state", "load_residual_state",
]

DEFAULT_BLOCK = 256

# every live bucketer, for optimizer-state persistence of the residuals
_ACTIVE: "weakref.WeakSet[GradBucketer]" = weakref.WeakSet()

# creation-order uid per bucketer: rank-consistent under SPMD program
# construction, and the piece that keeps two bucketers with identical
# layouts (same model wrapped twice) from colliding on exchange tags
_BUCKETER_SEQ = iter(range(1 << 62))


def bucket_mb() -> float:
    return float(_flags.env_flag("PADDLE_TPU_DP_BUCKET_MB"))


def overlap_enabled() -> bool:
    return bool(_flags.env_flag("PADDLE_TPU_DP_OVERLAP"))


def quantize_mode() -> str:
    mode = str(_flags.env_flag("PADDLE_TPU_DP_QUANTIZE")).strip().lower()
    if mode in ("", "0", "none", "fp32", "off"):
        return "none"
    if mode != "int8":
        raise ValueError(
            f"PADDLE_TPU_DP_QUANTIZE={mode!r}: supported modes are 'int8' "
            f"or unset (exact fp32 sum)")
    return mode


def quant_block() -> int:
    return max(8, int(_flags.env_flag("PADDLE_TPU_DP_QUANT_BLOCK")))


# ---------------------------------------------------------------------------
# bucket assignment (pure; identical on every rank by construction)
# ---------------------------------------------------------------------------


class BucketSlot:
    """One parameter's slice of a bucket's flat fp32 buffer."""

    __slots__ = ("name", "shape", "dtype", "offset", "numel")

    def __init__(self, name: str, shape: Tuple[int, ...], dtype: str,
                 offset: int):
        self.name = name
        self.shape = tuple(int(s) for s in shape)
        self.dtype = str(dtype)
        self.offset = int(offset)
        self.numel = int(np.prod(self.shape)) if self.shape else 1

    def key(self) -> Tuple:
        return (self.name, self.shape, self.dtype, self.offset)


class Bucket:
    __slots__ = ("index", "slots", "numel")

    def __init__(self, index: int, slots: List[BucketSlot]):
        self.index = index
        self.slots = slots
        self.numel = sum(s.numel for s in slots)

    @property
    def names(self) -> List[str]:
        return [s.name for s in self.slots]

    @property
    def nbytes_fp32(self) -> int:
        return self.numel * 4


def assign_buckets(entries: Sequence[Tuple[str, Sequence[int], Any]],
                   bucket_bytes: int) -> List[Bucket]:
    """Deterministic bucket layout over ``entries`` — (name, shape, dtype)
    in parameter BUILD order. Buckets fill in REVERSE build order (the
    order backward produces grads), each capped at ``bucket_bytes`` of
    fp32 payload; a single parameter larger than the cap gets a bucket of
    its own. Pure function of the entry sequence: any two ranks holding
    the same model produce byte-identical layouts."""
    cap = max(1, int(bucket_bytes))
    buckets: List[Bucket] = []
    slots: List[BucketSlot] = []
    offset = 0
    for name, shape, dtype in reversed(list(entries)):
        numel = int(np.prod(tuple(shape))) if tuple(shape) else 1
        if slots and (offset + numel) * 4 > cap:
            buckets.append(Bucket(len(buckets), slots))
            slots, offset = [], 0
        slots.append(BucketSlot(name, tuple(shape), str(dtype), offset))
        offset += numel
    if slots:
        buckets.append(Bucket(len(buckets), slots))
    return buckets


def layout_signature(buckets: Sequence[Bucket]) -> str:
    """Digest of the full layout (bucket -> ordered slot keys); equal on
    two ranks iff their bucket assignment is identical."""
    h = hashlib.sha1()
    for b in buckets:
        h.update(repr([s.key() for s in b.slots]).encode())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# blockwise int8 quantizer (shared by the eager path, the in-graph
# c_allreduce_bucket lowering, and tools/op_bench.py)
# ---------------------------------------------------------------------------


def quantize_blockwise(flat: jax.Array, block: int = DEFAULT_BLOCK
                       ) -> Tuple[jax.Array, jax.Array]:
    """Blockwise symmetric int8: pad ``flat`` (fp32, 1-D) to a multiple of
    ``block``, emit per-block scale = amax/127 (1.0 for all-zero blocks so
    dequant never divides by zero). Element error is bounded by scale/2.
    Returns (int8 padded payload, fp32 per-block scales)."""
    flat = flat.astype(jnp.float32).reshape(-1)
    n = flat.shape[0]
    pad = (-n) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    blocks = flat.reshape(-1, block)
    amax = jnp.max(jnp.abs(blocks), axis=1)
    scales = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(blocks / scales[:, None]), -127, 127)
    return q.astype(jnp.int8).reshape(-1), scales


def dequantize_blockwise(q: jax.Array, scales: jax.Array, numel: int,
                         block: int = DEFAULT_BLOCK) -> jax.Array:
    """Inverse of :func:`quantize_blockwise`: fp32 buffer of ``numel``
    elements (padding stripped)."""
    blocks = q.astype(jnp.float32).reshape(-1, block)
    return (blocks * scales[:, None]).reshape(-1)[:numel]


# jitted fast paths for the eager bucketer (one compiled program per
# bucket shape instead of a dozen eager op dispatches per step):
# encode = error-feedback compensate + quantize + residual update;
# decode = dequantize every rank's payload and sum.
@functools.partial(jax.jit, static_argnums=(2,))
def _ef_encode(flat: jax.Array, residual: jax.Array, block: int):
    comp = flat + residual
    q, scales = quantize_blockwise(comp, block)
    new_res = comp - dequantize_blockwise(q, scales, comp.shape[0], block)
    return q, scales, new_res


@functools.partial(jax.jit, static_argnums=(2, 3))
def _decode_sum(stacked_q: jax.Array, stacked_s: jax.Array, block: int,
                numel: int) -> jax.Array:
    n = stacked_q.shape[0]
    blocks = stacked_q.astype(jnp.float32).reshape(n, -1, block)
    deq = blocks * stacked_s[:, :, None]
    return deq.sum(axis=0).reshape(-1)[:numel]


def wire_nbytes(numel: int, mode: str, block: int = DEFAULT_BLOCK) -> int:
    """Bytes one rank actually contributes to the wire for a bucket of
    ``numel`` fp32 gradients: the fp32 buffer exact, or the int8 payload
    plus per-block fp32 scales when quantized."""
    if mode == "int8":
        padded = numel + ((-numel) % block)
        return padded + (padded // block) * 4
    return numel * 4


def predicted_step_bytes(buckets: Sequence[Bucket], mode: str,
                         block: int = DEFAULT_BLOCK) -> Dict[str, int]:
    """The comms PLAN of one full sync step over ``buckets``: the wire
    and fp32-logical byte totals ONE rank ships. This is the predicted
    side of ``shard_insight.reconcile`` for the eager DP path — the
    deterministic counterpart of the HLO collective summary for compiled
    programs. Exact bookkeeping of the same payloads
    ``_record_collective`` counts, so plan and measurement must agree
    near-perfectly over a measured window."""
    return {
        "wire_bytes": sum(wire_nbytes(b.numel, mode, block)
                          for b in buckets),
        "logical_bytes": sum(b.nbytes_fp32 for b in buckets),
    }


# ---------------------------------------------------------------------------
# transports: who moves a bucket's wire payload across ranks
# ---------------------------------------------------------------------------


class ProcessTransport:
    """Cross-process allgather over the JAX distributed runtime (the
    eager collective path's backend, coordination-KV fallback included).
    ``allgather`` returns each leaf stacked with a leading [nranks]
    axis. ``tag`` keys the KV exchange by content identity, so bucket
    payloads dispatched concurrently with the backward can never pair
    against another collective's sequence slot."""

    def __init__(self):
        self.nranks = jax.process_count()

    def allgather(self, tree, tag: Optional[str] = None):
        from . import collective as _collective

        return _collective._process_allgather(tree, tag=tag)


class LoopbackTransport:
    """Test/microbench transport: fabricates ``nranks`` peer payloads
    from the local one via ``peer_fn(tree, rank)`` (default: every peer
    echoes the local payload). Lets the full bucketer pipeline — pack,
    error feedback, quantize, reduce, unpack — run single-process."""

    def __init__(self, nranks: int = 2,
                 peer_fn: Optional[Callable[[Any, int], Any]] = None):
        self.nranks = int(nranks)
        self._peer_fn = peer_fn

    def allgather(self, tree, tag: Optional[str] = None):
        peers = [tree if self._peer_fn is None else self._peer_fn(tree, r)
                 for r in range(self.nranks)]
        return jax.tree_util.tree_map(
            lambda *leaves: jnp.stack([jnp.asarray(l) for l in leaves]),
            *peers)


# ---------------------------------------------------------------------------
# the bucketer
# ---------------------------------------------------------------------------


class GradBucketer:
    """Bucketed (optionally quantized) gradient all-reduce for one rank.

    Lifecycle per step: the backward engine calls :meth:`grad_ready` as
    each gradient finishes; a completed bucket dispatches immediately
    (async when overlap is on). :meth:`sync` dispatches any stragglers,
    blocks for the results, and returns {param_name: reduced fp-grad}.
    Error-feedback residuals live across steps (and across restarts via
    :func:`residual_state`)."""

    def __init__(self, params: Sequence[Any], *,
                 bucket_mb: Optional[float] = None,
                 overlap: Optional[bool] = None,
                 quantize: Optional[str] = None,
                 block: Optional[int] = None,
                 transport=None):
        entries = [(p.name, tuple(p.shape), str(p.dtype)) for p in params
                   if getattr(p, "trainable", True)]
        mb = globals()["bucket_mb"]() if bucket_mb is None else float(bucket_mb)
        self.bucket_bytes = max(1, int(mb * 1024 * 1024))
        self.overlap = overlap_enabled() if overlap is None else bool(overlap)
        self.quantize = (quantize_mode() if quantize is None
                         else (quantize or "none"))
        self.block = quant_block() if block is None else int(block)
        self.buckets = assign_buckets(entries, self.bucket_bytes)
        self.signature = layout_signature(self.buckets)
        self._slot_bucket = {s.name: b.index
                            for b in self.buckets for s in b.slots}
        self._transport = transport or ProcessTransport()
        self._lock = threading.Lock()
        self._pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
        self._residuals: Dict[int, jax.Array] = {}
        # pre-dispatch residual copies for the current step, so a
        # payload the sync fallback discards can have its residual
        # update rolled back (rollback_residual_for)
        self._residual_backup: Dict[int, jax.Array] = {}
        self._layout_verified = not isinstance(self._transport,
                                               ProcessTransport)
        self._uid = next(_BUCKETER_SEQ)
        self._step = 0
        self._reset_step()
        # observability: how each bucket got dispatched last step
        # ("hook" = overlapped with backward, "sync" = straggler sweep)
        self.last_dispatch_sources: Dict[int, str] = {}
        _ACTIVE.add(self)

    # -- per-step state -------------------------------------------------
    def _reset_step(self) -> None:
        self._staged: Dict[str, jax.Array] = {}
        self._pending: Dict[int, int] = {
            b.index: len(b.slots) for b in self.buckets}
        self._futures: Dict[int, Any] = {}
        self._step += 1

    def staged_value(self, name: str):
        return self._staged.get(name)

    # -- dispatch -------------------------------------------------------
    def bucket_index(self, name: str) -> Optional[int]:
        return self._slot_bucket.get(name)

    def grad_ready(self, name: str, value) -> None:
        """Stage one finished gradient; fires the bucket's collective as
        soon as its last member lands. Unknown names (non-parameter
        leaves sharing the tracer) are ignored."""
        idx = self._slot_bucket.get(name)
        if idx is None:
            return
        with self._lock:
            if not self._staged and not self._futures:
                # first grad of a NEW step: the previous step's rollback
                # window is over — drop the backup references so the
                # error-feedback state holds ONE copy per bucket, not two
                self._residual_backup.clear()
            if name in self._staged:
                # re-entrant backward on the same step (grad accumulation)
                # invalidates the in-flight payload; the sync fallback
                # path in DataParallel handles it
                self._staged[name] = value
                return
            self._staged[name] = value
            self._pending[idx] -= 1
            ready = self._pending[idx] == 0 and idx not in self._futures
        if ready:
            self._launch(idx, source="hook")

    def _launch(self, idx: int, source: str) -> None:
        bucket = self.buckets[idx]
        with self._lock:
            if idx in self._futures:
                return
            staged = {s.name: self._staged.get(s.name) for s in bucket.slots}
            self.last_dispatch_sources[idx] = source
            if self.overlap:
                if self._pool is None:
                    self._pool = concurrent.futures.ThreadPoolExecutor(
                        max_workers=1,
                        thread_name_prefix="paddle_tpu-dp-comms")
                self._futures[idx] = self._pool.submit(
                    self._reduce_bucket, bucket, staged)
            else:
                fut: concurrent.futures.Future = concurrent.futures.Future()
                try:
                    fut.set_result(self._reduce_bucket(bucket, staged))
                except Exception as e:  # surface at sync, like the async path
                    fut.set_exception(e)
                self._futures[idx] = fut

    def _pack(self, bucket: Bucket, staged: Dict[str, Any]) -> jax.Array:
        pieces = []
        for s in bucket.slots:
            v = staged.get(s.name)
            if v is None:
                # a parameter with no grad this step (unused branch):
                # zero-fill so every rank ships an identically-shaped
                # payload — the sum stays correct for ranks that did
                # produce this grad
                pieces.append(jnp.zeros((s.numel,), jnp.float32))
            else:
                pieces.append(jnp.asarray(v).astype(jnp.float32).reshape(-1))
        return pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)

    def _reduce_bucket(self, bucket: Bucket,
                       staged: Dict[str, Any]) -> jax.Array:
        """Runs on the comms thread (or inline without overlap): pack,
        error-feedback compensate, quantize, allgather, dequant-sum.
        Returns the reduced flat fp32 buffer (sum across ranks)."""
        from . import collective as _collective

        flat = self._pack(bucket, staged)
        op = ("all_reduce_bucket_int8" if self.quantize == "int8"
              else "all_reduce_bucket")
        wire = wire_nbytes(bucket.numel, self.quantize, self.block)
        _collective._record_collective(
            op, nbytes=wire, logical_nbytes=bucket.nbytes_fp32)
        # content-identity exchange tag: uid (creation order) + step +
        # bucket index. Pairing by identity instead of issue order keeps
        # a bucket hook-fired early on one rank and sweep-fired late on
        # another — or a user collective issued concurrently on the main
        # thread — from ever consuming this bucket's payload slot.
        tag = f"dp{self._uid}.s{self._step}.b{bucket.index}"
        # chaos sites on the comms thread: an armed delay/abort fires
        # per bucket exchange, exactly where a real straggler or torn
        # fabric would stall the overlapped collective (the abort's
        # typed Unavailable surfaces at sync() through the future)
        _chaos.delay(where=tag)
        _chaos.abort(where=tag)
        with _profiler.span(f"collective/{op}", cat="collective"):
            if self.quantize == "int8":
                res = self._residuals.get(bucket.index)
                if res is None:
                    res = jnp.zeros((bucket.numel,), jnp.float32)
                # one compiled program: compensate + quantize + the
                # residual update (the part the wire dropped rides into
                # the NEXT step's payload — error feedback)
                q, scales, new_res = _ef_encode(flat, res, self.block)
                self._residual_backup[bucket.index] = res
                self._residuals[bucket.index] = new_res
                stacked_q, stacked_s = self._allgather((q, scales), tag)
                return _decode_sum(jnp.asarray(stacked_q),
                                   jnp.asarray(stacked_s),
                                   self.block, bucket.numel)
            stacked = self._allgather(flat, tag)
            return jnp.asarray(stacked).sum(axis=0)

    def _allgather(self, tree, tag: Optional[str] = None):
        self._verify_layout_once()
        return self._transport.allgather(tree, tag=tag)

    def _verify_layout_once(self) -> None:
        if self._layout_verified:
            return
        self._layout_verified = True
        digest = np.uint32(zlib.crc32(self.signature.encode()))
        gathered = np.asarray(self._transport.allgather(
            jnp.uint32(digest), tag=f"dp{self._uid}.layout"))
        if not (gathered == digest).all():
            raise RuntimeError(
                "DP bucket layout diverged across ranks (digest "
                f"{self.signature[:12]} vs peers {gathered.tolist()}): "
                "ranks would all-reduce mismatched parameter slices and "
                "silently corrupt training. All ranks must build the "
                "same parameter list in the same order.")

    def predicted_step_bytes(self) -> Dict[str, int]:
        """This bucketer's per-step comms plan (wire + logical bytes)."""
        return predicted_step_bytes(self.buckets, self.quantize, self.block)

    # -- sync -----------------------------------------------------------
    def sync(self) -> Dict[str, jax.Array]:
        """Dispatch EVERY not-yet-fired bucket (index order), block for
        all in-flight collectives, and scatter the reduced buffers back
        per parameter. The sweep is all-or-nothing: once this step used
        the bucketer at all, every rank ships every bucket — a bucket
        with no local grads ships zero-fill — so the cross-rank
        collective stream stays aligned even when grad PRESENCE differs
        per rank (a data-dependently unused branch on one rank must not
        desync the exchange). Only the HOST-BLOCKING remainder lands in
        the goodput ``collective`` bucket: work that overlapped the
        backward is already paid for."""
        with self._lock:
            active = bool(self._futures) or bool(self._staged)
        if not active:
            self._reset_step()
            return {}
        for b in self.buckets:
            with self._lock:
                fire = b.index not in self._futures
            if fire:
                self._launch(b.index, source="sync")
        t0 = time.perf_counter()
        with _profiler.span("collective/all_reduce_bucket_sync",
                            cat="collective"):
            reduced_flats = {idx: fut.result()
                             for idx, fut in sorted(self._futures.items())}
            # jax dispatch is async: the grads are "needed" here, so the
            # device wait belongs to this window too
            for flat in reduced_flats.values():
                jax.block_until_ready(flat)
        _goodput.add("collective", time.perf_counter() - t0)
        out: Dict[str, jax.Array] = {}
        for idx, flat in reduced_flats.items():
            for s in self.buckets[idx].slots:
                if s.name not in self._staged:
                    continue  # no local grad: leave p.grad untouched
                piece = jax.lax.slice_in_dim(flat, s.offset,
                                             s.offset + s.numel)
                out[s.name] = piece.reshape(s.shape).astype(s.dtype)
        self._reset_step()
        return out

    def rollback_residual_for(self, name: str) -> None:
        """Undo this step's error-feedback residual update for the
        bucket carrying ``name``. The caller (DataParallel's sync
        fallback) discovered the shipped payload was stale — e.g. a
        second backward accumulated into the grad after dispatch — and
        is discarding it in favor of an exact re-reduce; the residual
        must not keep compensating for a transmission that was never
        applied. Idempotent per step (the backup entry pops)."""
        idx = self._slot_bucket.get(name)
        if idx is None:
            return
        with self._lock:
            old = self._residual_backup.pop(idx, None)
        if old is not None:
            self._residuals[idx] = old

    # -- residual persistence -------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        """Error-feedback residuals + the layout signature they belong
        to; empty in exact mode (nothing to compensate)."""
        if not self._residuals:
            return {}
        return {
            "signature": self.signature,
            "quantize": self.quantize,
            "residuals": {str(i): np.asarray(r)
                          for i, r in sorted(self._residuals.items())},
        }

    def set_state_dict(self, state: Dict[str, Any]) -> None:
        if not state:
            return
        if state.get("signature") != self.signature:
            raise ValueError(
                "dp_comms residual state belongs to a different bucket "
                f"layout ({state.get('signature')!r} != {self.signature!r});"
                " restoring it would compensate the wrong parameters")
        self._residuals = {
            int(i): jnp.asarray(r, jnp.float32)
            for i, r in (state.get("residuals") or {}).items()}


# ---------------------------------------------------------------------------
# optimizer-state integration: the residuals ride the optimizer ckpt
# ---------------------------------------------------------------------------


def residual_state() -> Dict[str, Any]:
    """Serializable error-feedback state of every live bucketer (keyed
    by layout signature). ``Optimizer.state_dict`` embeds this under
    ``__dp_comms__`` so a restart restores the compensation buffers with
    the moments."""
    out: Dict[str, Any] = {}
    for b in list(_ACTIVE):
        st = b.state_dict()
        if st:
            out[st["signature"]] = st
    return out


def load_residual_state(state: Dict[str, Any]) -> int:
    """Restore residuals onto live bucketers by layout signature;
    returns how many bucketers matched. Unmatched entries are ignored
    (a differently-arranged restart starts its compensation fresh)."""
    matched = 0
    for b in list(_ACTIVE):
        st = (state or {}).get(b.signature)
        if st:
            b.set_state_dict(st)
            matched += 1
    return matched
