"""Sharding recipes: one mesh, every strategy.

The GSPMD-native parallelism layer (ROADMAP item 1, the MLPerf TPU-pod
playbook of Kumar et al., arXiv:1909.09756): instead of Fleet rewriting
the training block with per-gradient ``c_*`` collective ops, a *recipe*
declares how one ``jax.sharding.Mesh`` with named axes (``dp`` /
``fsdp`` / ``tp``) lays out parameters, optimizer state and the batch —
and the whole step is pjit-lowered with in/out shardings derived from
the recipe, letting XLA's SPMD partitioner place every collective.

This table is the ONE shared source of recipe definitions: the runtime
mesh build (``fleet.distributed_optimizer`` via
``strategy.sharding_recipe``, the executor's mesh-program compile path)
and the AOT planner (``framework/topology.py`` + ``tools/topo_plan.py``)
both resolve recipes here, so a plan can never drift from what the
executor actually lays out.

Three primitives:

- :class:`SpecLayout` — canonical axis names and PartitionSpecs (the
  ``SpecLayout`` pattern from SNIPPETS.md [2]);
- :class:`Recipe` / :data:`RECIPES` — named presets (``dp``, ``fsdp``,
  ``tp`` and hybrids) mapping a device count onto mesh axes;
- :class:`ResolvedRecipe` — a recipe bound to a device count: builds
  the mesh, the parameter sharding rules (tensor-parallel rules first,
  the ZeRO-3 ``fsdp`` dim-0 catch-all behind them — optimizer moments
  ride the same rules via the accumulator-name variants), the batch
  PartitionSpec, the pjit in/out shardings for the executor's
  ``(feeds, mut, const, seed)`` calling convention, and the analytic
  comms plan (:meth:`ResolvedRecipe.predicted_collectives`) the
  MULTICHIP bench reconciles against the HLO-extracted plan.

The explicit-collectives path (``c_allreduce_bucket`` insertion,
PR 8) remains the multi-process fallback and the A/B baseline: recipes
apply only where every mesh device is addressable from one controller.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "SpecLayout", "Recipe", "ResolvedRecipe", "RECIPES",
    "GPT_TP_RULES", "FSDP_RULES", "STATE_SLOT_SUFFIX",
    "recipe_names", "resolve_recipe", "state_rule_variants",
    "apply_to_program", "axis_factorizations", "enumerate_layouts",
    "parse_layout_spec",
]


# ---------------------------------------------------------------------------
# axis layout (SNIPPETS.md [2] SpecLayout pattern, repo axis conventions)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SpecLayout:
    """Canonical mesh-axis names. The repo convention is ``dp``/``fsdp``/
    ``tp`` (topology.AXIS_ALIASES maps the ROADMAP's ``data`` onto
    ``dp``); batch shards jointly over (dp, fsdp), parameters over fsdp
    dim 0 (ZeRO-3) and/or the Megatron tp dims."""

    data_axis: str = "dp"
    fsdp_axis: str = "fsdp"
    tp_axis: str = "tp"

    def batch_axes(self, axes: Dict[str, int]) -> Tuple[str, ...]:
        """The mesh axes the leading batch dim shards over (size-1 axes
        excluded: they partition nothing and only add spec noise)."""
        return tuple(a for a in (self.data_axis, self.fsdp_axis)
                     if int(axes.get(a, 1)) > 1)

    def batch_spec(self, axes: Dict[str, int]):
        from jax.sharding import PartitionSpec

        b = self.batch_axes(axes)
        if not b:
            return PartitionSpec()
        return PartitionSpec(b if len(b) > 1 else b[0])


# Megatron-style tensor-parallel rules for the flagship GPT parameter
# names (models/gpt.py delegates here — one table, no drift).
# Column-parallel: qkv + ffn-in shard the output dim; row-parallel:
# attn proj + ffn-out shard the input dim; embeddings shard the vocab dim.
GPT_TP_RULES: List[Tuple[str, Tuple]] = [
    (r".*\.attn\.[qkv]\.w$", (None, "tp")),
    (r".*\.attn\.proj\.w$", ("tp", None)),
    (r".*\.mlp\.fc_in\.w$", (None, "tp")),
    (r".*\.mlp\.fc_in\.b$", ("tp",)),
    (r".*\.mlp\.fc_out\.w$", ("tp", None)),
    (r".*\.attn\.[qkv]\.b$", ("tp",)),
    (r"gpt\.wte$", ("tp", None)),
    (r"gpt\.lm_head\.w$", (None, "tp")),
]

# ZeRO-3/FSDP catch-all: dim 0 of everything (params, moments, anything
# scope-resident) shards over fsdp; mesh.clean_spec degrades it away
# where dim 0 does not divide (scalars, beta pows, odd dims replicate).
FSDP_RULES: List[Tuple[str, Tuple]] = [(r".*", ("fsdp",))]

# optimizer accumulator names are `<param>_<slot>_<n>`
# (optimizer.py _add_accumulator via unique_name.generate); a rule that
# shards a parameter must shard its same-shaped moments identically or
# every step pays a reshard inside the update op
STATE_SLOT_SUFFIX = (r"_(?:moment1|moment2|momentum_acc|moment|velocity|"
                     r"inf_norm|mean_square|mean_grad|squared_accumulator|"
                     r"avg_squared_grad|avg_squared_update)_\d+$")


def state_rule_variants(rules: Sequence[Tuple[str, Tuple]]
                        ) -> List[Tuple[str, Tuple]]:
    """For every ``$``-anchored parameter rule, the accumulator-name
    variant carrying the same spec (same-shaped slots only — scalar
    beta-pow accumulators degrade to replicated via clean_spec)."""
    out: List[Tuple[str, Tuple]] = []
    for pat, axes in rules:
        if pat.endswith("$"):
            out.append((pat[:-1] + STATE_SLOT_SUFFIX, axes))
    return out


# ---------------------------------------------------------------------------
# the recipe table
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Recipe:
    """A named parallelism strategy: an ordered tuple of (axis, size)
    where size None means "fill with the remaining devices". Hybrid
    presets default their minor axes to 2 and are overridable per axis
    (``resolve_recipe(name, n, overrides={"tp": 4})``)."""

    name: str
    axes: Tuple[Tuple[str, Optional[int]], ...]
    description: str = ""

    def resolve(self, n_devices: int,
                overrides: Optional[Dict[str, int]] = None
                ) -> "ResolvedRecipe":
        n = int(n_devices)
        if n < 1:
            raise ValueError(f"recipe {self.name!r} needs >= 1 device")
        overrides = {k: int(v) for k, v in (overrides or {}).items()
                     if v is not None}
        declared = {ax for ax, _ in self.axes}
        unknown = sorted(set(overrides) - declared)
        if unknown:
            raise ValueError(
                f"recipe {self.name!r} has no axis {unknown} to override "
                f"(declared: {sorted(declared)}) — a silently ignored "
                f"override would train a different strategy than asked")
        bad = {k: v for k, v in overrides.items() if v < 1}
        if bad:
            raise ValueError(
                f"recipe {self.name!r}: override axis sizes must be "
                f">= 1, got {bad}")
        sizes: Dict[str, Optional[int]] = {}
        fill_axis = None
        for ax, size in self.axes:
            size = overrides.get(ax, size)
            if size is None:
                if fill_axis is not None:
                    raise ValueError(
                        f"recipe {self.name!r}: two fill axes "
                        f"({fill_axis!r}, {ax!r}) — fix all but one size")
                fill_axis = ax
                sizes[ax] = None
            else:
                sizes[ax] = int(size)
        fixed = 1
        for s in sizes.values():
            if s is not None:
                fixed *= s
        if fill_axis is not None:
            if n % fixed != 0:
                raise ValueError(
                    f"recipe {self.name!r}: fixed axes use {fixed} "
                    f"device(s), which does not divide {n}")
            sizes[fill_axis] = n // fixed
        else:
            if fixed != n:
                raise ValueError(
                    f"recipe {self.name!r} lays out {fixed} device(s) "
                    f"but {n} exist")
        resolved = {ax: int(s) for ax, s in sizes.items()}
        total = 1
        for s in resolved.values():
            total *= s
        if total != n:
            raise ValueError(
                f"recipe {self.name!r}: axes {resolved} cover {total} "
                f"of {n} devices")
        return ResolvedRecipe(name=self.name, axes=resolved)


# minor axes of hybrids default to 2 (overridable); the first axis fills
RECIPES: Dict[str, Recipe] = {
    r.name: r for r in (
        Recipe("dp", (("dp", None),),
               "pure data parallel: batch shards over every device, "
               "parameters/state replicated; GSPMD emits the gradient "
               "all-reduce"),
        Recipe("fsdp", (("fsdp", None),),
               "ZeRO-3/FSDP: parameters + optimizer state shard dim 0, "
               "batch shards too; GSPMD emits gather-at-use + "
               "reduce-scatter"),
        Recipe("tp", (("tp", None),),
               "Megatron tensor parallel: qkv/ffn-in column-sharded, "
               "proj/ffn-out row-sharded, batch replicated; GSPMD emits "
               "the activation all-reduces"),
        Recipe("dp_fsdp", (("dp", None), ("fsdp", 2)),
               "hybrid ZeRO: batch over (dp, fsdp), state sharded over "
               "the fsdp subgroup only"),
        Recipe("dp_tp", (("dp", None), ("tp", 2)),
               "data parallel over tensor-parallel subgroups"),
        Recipe("fsdp_tp", (("fsdp", None), ("tp", 2)),
               "FSDP over tensor-parallel subgroups"),
        Recipe("dp_fsdp_tp", (("dp", None), ("fsdp", 2), ("tp", 2)),
               "the full 3D hybrid"),
    )
}


def recipe_names() -> List[str]:
    return list(RECIPES)


def resolve_recipe(name: str, n_devices: int,
                   overrides: Optional[Dict[str, int]] = None
                   ) -> "ResolvedRecipe":
    """``RECIPES[name].resolve`` with a helpful error; also accepts an
    inline ``{"dp": 2, "tp": 4}``-style dict in place of a name."""
    if isinstance(name, dict):
        return Recipe("custom", tuple((k, int(v)) for k, v in name.items())
                      ).resolve(n_devices, overrides)
    key = str(name).strip().lower()
    if key not in RECIPES:
        raise ValueError(
            f"unknown sharding recipe {name!r} (one of {recipe_names()})")
    return RECIPES[key].resolve(n_devices, overrides)


# ---------------------------------------------------------------------------
# candidate enumeration (the auto-planner's search space)
# ---------------------------------------------------------------------------


PLAN_AXES: Tuple[str, ...] = ("dp", "fsdp", "tp")


def axis_factorizations(n_devices: int,
                        axes: Sequence[str] = PLAN_AXES
                        ) -> List[Dict[str, int]]:
    """Every ordered assignment of axis sizes (each >= 1) whose product
    is ``n_devices``: the complete mesh-layout search space over the
    named axes. For n = p^k over 3 axes this is the stars-and-bars
    count — 10 layouts at n=8 — small enough to score exhaustively."""
    n = int(n_devices)
    if n < 1:
        raise ValueError(f"need >= 1 device, got {n}")
    axes = tuple(axes)
    if not axes:
        raise ValueError("need >= 1 axis name")

    out: List[Dict[str, int]] = []

    def rec(i: int, remaining: int, acc: Dict[str, int]) -> None:
        if i == len(axes) - 1:
            out.append({**acc, axes[i]: remaining})
            return
        d = 1
        while d <= remaining:
            if remaining % d == 0:
                rec(i + 1, remaining // d, {**acc, axes[i]: d})
            d += 1

    rec(0, n, {})
    return out


def _canonical_axes(axes: Dict[str, int]) -> Tuple[Tuple[str, int], ...]:
    """Size-1 axes partition nothing: {dp:8, fsdp:1, tp:1} and the `dp`
    preset's {dp:8} are the same layout, so dedup on the >1 axes (in
    PLAN_AXES order)."""
    return tuple((a, int(axes[a])) for a in PLAN_AXES
                 if int(axes.get(a, 1)) > 1)


def enumerate_layouts(n_devices: int,
                      axes: Sequence[str] = PLAN_AXES
                      ) -> List["ResolvedRecipe"]:
    """The auto-planner's candidate set: every distinct mesh layout of
    ``n_devices`` over the plan axes (named presets plus the remaining
    axis-size factorizations), deduplicated by canonical axes. A layout
    a preset resolves to carries the preset's name; the rest are
    ``custom`` and render as explicit ``axis=size`` specs
    (:attr:`ResolvedRecipe.spec`)."""
    named: Dict[Tuple, str] = {}
    for name in RECIPES:
        try:
            resolved = RECIPES[name].resolve(n_devices)
        except ValueError:
            continue  # preset does not divide this device count
        named.setdefault(_canonical_axes(resolved.axes), name)

    out: List[ResolvedRecipe] = []
    seen = set()
    for layout in axis_factorizations(n_devices, axes):
        key = _canonical_axes(layout)
        if key in seen:
            continue
        seen.add(key)
        # drop size-1 axes from the candidate mesh (they partition
        # nothing and would only widen every PartitionSpec); a fully
        # trivial layout (n=1) keeps one dp axis so a mesh still builds
        kept = {a: s for a, s in layout.items() if s > 1} or {"dp": 1}
        out.append(ResolvedRecipe(name=named.get(key, "custom"),
                                  axes=kept))
    return out


def parse_layout_spec(text: str):
    """A layout spec string -> what :func:`resolve_recipe` accepts: a
    named preset (``"fsdp"``) passes through, an explicit
    ``"dp=2,fsdp=4"`` becomes an ordered {axis: size} dict."""
    text = str(text).strip()
    if "=" not in text:
        return text.lower()
    out: Dict[str, int] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad layout entry {part!r} (want axis=size)")
        k, v = part.split("=", 1)
        out[k.strip()] = int(v)
    if not out:
        raise ValueError(f"empty layout spec {text!r}")
    return out


# ---------------------------------------------------------------------------
# a recipe bound to a device count
# ---------------------------------------------------------------------------


@dataclass
class ResolvedRecipe:
    name: str
    axes: Dict[str, int]
    layout: SpecLayout = field(default_factory=SpecLayout)

    @property
    def n_devices(self) -> int:
        n = 1
        for s in self.axes.values():
            n *= int(s)
        return n

    @property
    def batch_axes(self) -> Tuple[str, ...]:
        return self.layout.batch_axes(self.axes)

    @property
    def tp(self) -> int:
        return int(self.axes.get(self.layout.tp_axis, 1))

    @property
    def fsdp(self) -> int:
        return int(self.axes.get(self.layout.fsdp_axis, 1))

    @property
    def dp(self) -> int:
        return int(self.axes.get(self.layout.data_axis, 1))

    @property
    def spec(self) -> str:
        """Canonical spec string: the preset name when this is a named
        recipe, else the explicit ``axis=size`` form (size-1 axes
        dropped) — round-trips through :func:`parse_layout_spec` and is
        what tools (mesh_bench --validate workers, plan reports) key
        candidates by."""
        if self.name in RECIPES:
            return self.name
        parts = [f"{a}={s}" for a, s in self.axes.items() if int(s) > 1]
        return ",".join(parts) or "dp=1"

    def mesh(self, devices: Optional[Sequence] = None):
        from .mesh import make_mesh

        return make_mesh(dict(self.axes), devices)

    def sharding_rules(self, tp_rules: Optional[Sequence[Tuple[str, Tuple]]]
                       = None) -> List[Tuple[str, Tuple]]:
        """Parameter/state placement rules, first-match-wins: tp rules
        (and their accumulator variants) first, then the fsdp dim-0
        catch-all — exactly the ordering the FSDP dry-run leg proved."""
        rules: List[Tuple[str, Tuple]] = []
        if self.tp > 1:
            base = list(tp_rules if tp_rules is not None else GPT_TP_RULES)
            rules += base + state_rule_variants(base)
        if self.fsdp > 1:
            rules += FSDP_RULES
        return rules

    def batch_spec(self):
        return self.layout.batch_spec(self.axes)

    def to_dict(self) -> dict:
        return {"name": self.name, "axes": dict(self.axes),
                "n_devices": self.n_devices,
                "batch_axes": list(self.batch_axes)}

    # -- pjit shardings for the executor calling convention -------------

    def feed_sharding(self, mesh, value):
        """NamedSharding for one feed: leading dim over the batch axes
        when it divides (clean_spec degrades otherwise — scalar lr feeds
        replicate)."""
        from jax.sharding import NamedSharding

        from .mesh import clean_spec

        shape = tuple(getattr(value, "shape", ()) or ())
        return NamedSharding(mesh, clean_spec(self.batch_spec(), shape, mesh))

    def param_sharding(self, mesh, name: str, value,
                       rules: Optional[Sequence[Tuple[str, Tuple]]] = None):
        from jax.sharding import NamedSharding

        from .mesh import clean_spec, spec_for

        shape = tuple(getattr(value, "shape", ()) or ())
        rules = rules if rules is not None else self.sharding_rules()
        return NamedSharding(mesh, clean_spec(spec_for(name, rules),
                                              shape, mesh))

    def jit_shardings(self, mesh, feed_vals: Dict[str, Any],
                      mut: Dict[str, Any], const: Dict[str, Any],
                      rules: Optional[Sequence[Tuple[str, Tuple]]] = None,
                      updated: Optional[Dict[str, Any]] = None):
        """(in_shardings, out_shardings) for the executor's jitted
        ``fn(feeds, mut, const, seed_step) -> (fetches, new_params,
        next_seed, probes)``. Fetches/seed/probes are replicated
        (fetches are losses/metrics — host-bound either way); parameters
        keep the recipe placement on BOTH sides so donation aliases
        shard-for-shard and optimizer state never leaves its shards.
        ``updated`` names the new_params output entries (shape carriers;
        a superset of ``mut`` when the block writes persistables it
        never reads — defaults to ``mut``)."""
        from jax.sharding import NamedSharding, PartitionSpec

        rules = rules if rules is not None else self.sharding_rules()
        repl = NamedSharding(mesh, PartitionSpec())
        feeds_sh = {k: self.feed_sharding(mesh, v)
                    for k, v in feed_vals.items()}
        mut_sh = {k: self.param_sharding(mesh, k, v, rules)
                  for k, v in mut.items()}
        const_sh = {k: self.param_sharding(mesh, k, v, rules)
                    for k, v in const.items()}
        in_shardings = (feeds_sh, mut_sh, const_sh, repl)
        out_params = {k: self.param_sharding(mesh, k, v, rules)
                      for k, v in (updated if updated is not None
                                   else mut).items()}
        # pytree-prefix semantics: one replicated leaf covers the whole
        # fetches list / probes list regardless of length
        out_shardings = (repl, out_params, repl, repl)
        return in_shardings, out_shardings

    # -- the analytic comms plan (per device, per step) ------------------

    def planned_kinds(self) -> Tuple[str, ...]:
        """Collective kinds this recipe licenses GSPMD to emit. Anything
        the HLO carries outside this set is an unplanned collective —
        the ``measured_only`` tripwire the MULTICHIP bench fails on.
        Reduction kinds are interchangeable under GSPMD (an all-reduce
        may compile as reduce-scatter + all-gather and vice versa), so
        any sharded recipe licenses the reduction family; recipes that
        shard parameters additionally license the reshard primitives
        (collective-permute / all-to-all) GSPMD uses to move a value
        between the rule layout and the batch layout."""
        kinds = set()
        if self.n_devices > 1:
            # even pure-dp programs all-reduce the scalar loss mean
            kinds.update(("all-reduce",))
        if self.dp > 1 or self.fsdp > 1:
            kinds.update(("all-reduce", "reduce-scatter", "all-gather"))
        if self.fsdp > 1 or self.tp > 1:
            kinds.update(("all-reduce", "all-gather", "reduce-scatter",
                          "collective-permute", "all-to-all"))
        return tuple(sorted(kinds))

    def predicted_collectives(self, param_entries: Sequence[Tuple[str, Tuple[int, ...], int]],
                              batch: int, seq: int, d_model: int,
                              n_layer: int,
                              dtype_bytes: int = 4,
                              lmhead: str = "chunked") -> Dict[str, Any]:
        """The recipe's analytic comms plan for one step on one device,
        in shard_insight's payload conventions (all-reduce counts the
        full buffer, gather/scatter the local shard). This is the
        *predicted* side of the MULTICHIP reconciliation; the
        HLO-extracted summary is the measured side, and the two must
        agree within PADDLE_TPU_SHARD_INSIGHT_BOUND.

        ``param_entries``: (name, shape, itemsize) for every trainable
        parameter. The model (documented, deliberately coarse — a plan,
        not a benchmark; calibrated against XLA's observed GSPMD
        choices on this repo's train programs):

        - batch-sharded recipes (dp and/or fsdp) reduce gradients with
          full-buffer all-reduces at the TP-resident grad size (XLA
          prefers all-reduce over reduce-scatter+gather here even for
          fsdp-sharded parameters — the memory win comes from state
          placement, not the reduction);
        - fsdp: parameters additionally gather at use in forward and
          again in backward (2x the resident fsdp-sharded bytes,
          shard-side convention);
        - tp: Megatron activation all-reduces — 2 per layer forward +
          2 backward of the [B, S, D] activation, plus lm-head /
          embedding terms of a few activation sizes (vocab-sharded
          logits reduce their softmax stats and hidden grads).

        ``lmhead`` states which loss path the program compiled
        (``io["lm_head_impl"]``): under ``"pallas"`` the tp lm-head
        terms are priced explicitly — the fused kernel's forward ships
        3 f32 row stats per token (one pmax + one psum across tp) and
        its backward one [B, S, D] hidden-grad all-reduce — replacing
        one of the coarse activation-sized lm-head terms of the
        chunked/GSPMD model (the embedding lookup's pair stays).
        """
        from .mesh import clean_spec, spec_for

        rules = self.sharding_rules()
        mesh_sizes = dict(self.axes)

        def shard_factor(spec_axes) -> int:
            f = 1
            for e in spec_axes:
                if e is None:
                    continue
                for ax in (e if isinstance(e, (tuple, list)) else (e,)):
                    f *= int(mesh_sizes.get(ax, 1))
            return f

        class _FakeMesh:
            shape = mesh_sizes

        resident_total = 0      # per-device param bytes after sharding
        tp_resident_total = 0   # param bytes after TP sharding only
        fsdp_sharded = 0        # per-device bytes of fsdp-sharded params
        tp_axis, fsdp_axis = self.layout.tp_axis, self.layout.fsdp_axis
        for name, shape, itemsize in param_entries:
            nbytes = int(itemsize)
            for s in shape:
                nbytes *= int(s)
            spec = tuple(clean_spec(spec_for(name, rules), shape,
                                    _FakeMesh()))
            f = shard_factor(spec)
            resident = nbytes // max(1, f)
            resident_total += resident
            flat = [a for e in spec if e is not None
                    for a in (e if isinstance(e, (tuple, list)) else (e,))]
            tp_f = self.tp if tp_axis in flat else 1
            tp_resident_total += nbytes // max(1, tp_f)
            if fsdp_axis in flat:
                fsdp_sharded += resident

        plan: Dict[str, int] = {}
        # instruction-shaped records carrying the axes each term spans,
        # so topology.axis_bytes_breakdown attributes the ANALYTIC plan
        # per mesh axis through the same function that attributes the
        # HLO-extracted one (the auto-planner's planned_by_axis view)
        instructions: List[Dict[str, Any]] = []
        batch_axes = [a for a, n in ((self.layout.data_axis, self.dp),
                                     (self.layout.fsdp_axis, self.fsdp))
                      if n > 1]
        if self.dp > 1 or self.fsdp > 1:
            # the gradient reduction: full-buffer all-reduce at the
            # TP-resident size (fsdp shards state, not the reduction)
            plan["all-reduce"] = (plan.get("all-reduce", 0)
                                  + tp_resident_total)
            instructions.append({
                "kind": "all-reduce",
                "payload_bytes": int(tp_resident_total),
                "group_size": int(self.dp * self.fsdp),
                "group_axes": list(batch_axes),
                "term": "grad_reduction"})
        if self.fsdp > 1:
            plan["all-gather"] = plan.get("all-gather", 0) + 2 * fsdp_sharded
            instructions.append({
                "kind": "all-gather",
                "payload_bytes": int(2 * fsdp_sharded),
                "group_size": int(self.fsdp),
                "group_axes": [fsdp_axis],
                "term": "fsdp_param_gather"})
        if self.tp > 1:
            # the Megatron all-reduces move the PER-DEVICE activation:
            # [B / (dp*fsdp), S, D] — the batch dims shard over the
            # batch axes, so a hybrid recipe's tp traffic shrinks with
            # the batch sharding (per-device convention throughout)
            local_batch = max(1, int(batch) // max(1, self.dp * self.fsdp))
            act = local_batch * int(seq) * int(d_model) * int(dtype_bytes)
            lm_terms = 4
            if str(lmhead) == "pallas":
                # the fused kernel's own collectives are priced exactly
                # below; one coarse activation-sized lm-head term drops
                # out of the (4L + 4) model (the kernel's dx reduce is
                # the remaining activation-sized one, the stats pair is
                # tokens-sized)
                lm_terms = 3
                tokens = local_batch * int(seq)
                stats_bytes = 3 * tokens * 4  # (max, sum-exp, picked) f32
                plan["all-reduce"] = plan.get("all-reduce", 0) + stats_bytes
                instructions.append({
                    "kind": "all-reduce",
                    "payload_bytes": int(stats_bytes),
                    "group_size": int(self.tp),
                    "group_axes": [tp_axis],
                    "term": "lmhead_ce_fused_stats"})
            tp_bytes = (4 * int(n_layer) + lm_terms) * act
            plan["all-reduce"] = plan.get("all-reduce", 0) + tp_bytes
            instructions.append({
                "kind": "all-reduce",
                "payload_bytes": int(tp_bytes),
                "group_size": int(self.tp),
                "group_axes": [tp_axis],
                "term": "tp_activation_reduce"})
        total = sum(plan.values())
        return {
            "by_kind": dict(sorted(plan.items())),
            "payload_bytes_total": int(total),
            "planned_kinds": list(self.planned_kinds()),
            "resident_param_bytes": int(resident_total),
            "tp_resident_param_bytes": int(tp_resident_total),
            "fsdp_sharded_bytes": int(fsdp_sharded),
            "instructions": instructions,
        }

    def payload_by_axis(self, param_entries: Sequence[Tuple[str, Tuple[int, ...], int]],
                        batch: int, seq: int, d_model: int, n_layer: int,
                        dtype_bytes: int = 4,
                        lmhead: str = "chunked") -> Dict[str, dict]:
        """The analytic plan attributed per mesh axis: one step's
        predicted collective bytes routed through
        ``topology.axis_bytes_breakdown`` exactly as the HLO summary
        is — the attribution weights commswatch pro-rates the measured
        collective wall with (``configure_attribution``), and the byte
        split the planner prices per link class."""
        from ..framework import topology as _topo

        plan = self.predicted_collectives(
            param_entries, batch=batch, seq=seq, d_model=d_model,
            n_layer=n_layer, dtype_bytes=dtype_bytes, lmhead=lmhead)
        mesh_sizes = dict(self.axes)

        class _FakeMesh:
            shape = mesh_sizes

        return _topo.axis_bytes_breakdown(plan, _FakeMesh())


# ---------------------------------------------------------------------------
# program wiring (the fleet/executor integration point)
# ---------------------------------------------------------------------------


def apply_to_program(program, resolved: ResolvedRecipe,
                     devices: Optional[Sequence] = None,
                     tp_rules: Optional[Sequence[Tuple[str, Tuple]]] = None):
    """Attach a resolved recipe to a static program: the mesh, the
    sharding rules (appended after any rules already registered, e.g.
    ShardingOptimizer's exact-name state rules) and the recipe record
    the executor compiles in/out shardings from. Returns the mesh."""
    mesh = resolved.mesh(devices)
    program._mesh = mesh
    rules = resolved.sharding_rules(tp_rules)
    existing = list(getattr(program, "_sharding_rules", []))
    program._sharding_rules = existing + [r for r in rules
                                          if r not in existing]
    program._sharding_recipe = resolved
    # replacing a recipe after the program compiled must not reuse the
    # old executable's shardings or skip the scope reshard: the compile
    # cache and the per-scope prepare set both key on program version
    program._bump_version()
    return mesh
