"""Device mesh + sharding-rule helpers — the TPU-native replacement for the
reference's NCCL ring plumbing.

Where the reference wires `ring_id`-keyed NCCL communicators into op handles
(/root/reference/paddle/fluid/platform/collective_helper.h:62,
nccl_helper.h:185) and inserts explicit c_allreduce ops per gradient, the
TPU build states *placement*: a `jax.sharding.Mesh` over ICI plus
per-parameter `PartitionSpec`s derived from name rules. XLA/GSPMD then
derives every collective (all-reduce for row-parallel matmuls and data
parallel grads, all-gather for column-parallel outputs) and schedules it on
ICI — the c_* ops remain for program-level parity but placement is the
primary mechanism (SURVEY.md §5.8).
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh with named axes, e.g. make_mesh({'dp': 2, 'tp': 4}).
    Axis sizes must multiply to the device count."""
    devices = list(devices if devices is not None else jax.devices())
    shape = tuple(axes.values())
    n = int(np.prod(shape))
    if n != len(devices):
        raise ValueError(f"mesh {axes} needs {n} devices, got {len(devices)}")
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axes.keys()))


def spec_for(name: str, rules: Sequence[Tuple[str, Tuple]], default=PartitionSpec()) -> PartitionSpec:
    """First regex rule matching `name` wins; rules map to PartitionSpec."""
    for pattern, axes in rules:
        if re.fullmatch(pattern, name):
            return PartitionSpec(*axes)
    return default


def clean_spec(spec, shape: Sequence[int], mesh: Mesh) -> PartitionSpec:
    """Degrade a PartitionSpec for a concrete shape: axes that are
    absent from the mesh or do not divide their dimension are dropped
    (that dim replicates) — e.g. tp over an odd vocab, or a last
    partial batch under a joint ('dp','fsdp') entry (the whole tuple
    drops when the dim does not divide the axes' combined size). THE
    one degrade rule: shard_scope applies it, shard_insight.verify_scope
    asserts against it, tools/topo_plan.py plans with it."""
    entries = tuple(spec) + (None,) * (len(shape) - len(tuple(spec)))
    clean = []
    for dim, ax in zip(shape, entries):
        if ax is not None:
            axes = tuple(ax) if isinstance(ax, (tuple, list)) else (ax,)
            total = 1
            for a in axes:
                size = mesh.shape.get(a)
                total = None if (total is None or size is None) \
                    else total * int(size)
            if total is None or dim % total != 0:
                ax = None
        clean.append(ax)
    return PartitionSpec(*clean)


def shard_scope(scope, mesh: Mesh, rules: Sequence[Tuple[str, Tuple]]):
    """device_put every scope array onto the mesh per the name rules
    (parameters the rules miss are replicated). In-place: the scope keeps
    the same names, now holding sharded jax.Arrays — the executor's jit
    then compiles the whole step with GSPMD propagation from these."""
    for name in list(scope.all_var_names()):
        arr = scope.get(name)
        if not hasattr(arr, "shape"):
            continue
        spec = spec_for(name, rules)
        sharding = NamedSharding(mesh, clean_spec(spec, arr.shape, mesh))
        scope.set(name, jax.device_put(arr, sharding))


def shard_batch(mesh: Mesh, arr, axis="dp"):
    """Shard the leading (batch) dim of a host array across `axis` — a
    mesh axis name or a tuple of names (FSDP shards batch over
    ('dp', 'fsdp') jointly)."""
    axes = axis if isinstance(axis, tuple) else (axis,)
    axes = tuple(a for a in axes if a in mesh.axis_names)
    spec = [None] * arr.ndim
    if axes:
        spec[0] = axes if len(axes) > 1 else axes[0]
    return jax.device_put(arr, NamedSharding(mesh, PartitionSpec(*spec)))


def replicate(mesh: Mesh, arr):
    return jax.device_put(arr, NamedSharding(mesh, PartitionSpec()))
