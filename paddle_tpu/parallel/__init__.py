"""paddle.distributed equivalent: mesh-based parallelism over XLA
collectives (see SURVEY.md 2.9 / 5.8 for the reference inventory)."""
from . import env, mesh, recipes
from .env import ParallelEnv, get_rank, get_world_size, init_parallel_env
from .mesh import make_mesh, shard_batch, shard_scope, spec_for
from .recipes import RECIPES, ResolvedRecipe, SpecLayout, resolve_recipe
