"""Pipeline parallelism: program sectioning + F-then-B microbatch schedule.

Counterpart of the reference pipeline stack
(/root/reference/paddle/fluid/framework/pipeline_trainer.cc:122 per-section
scopes + microbatch scope arrays, section_worker.cc:107-174 run
num_microbatches forward then backward then optimize filtered by op role,
python/paddle/fluid/optimizer.py:3666 PipelineOptimizer splitting by
device_guard). TPU translation:

- Stages are tagged with `device_guard('tpu:<s>')` (attr `op_device`);
  grad ops inherit the tag because the desc backward copies forward attrs.
- The program splits into per-stage *sections*: forward, backward and
  optimizer op lists per stage, with an explicit boundary-variable
  interface between them (the SectionWorker's scope handoff, made
  explicit).
- Execution (framework/executor.py _run_pipeline): each section lowers to
  one jitted XLA program pinned to its stage's device row of a 'pp' mesh
  axis; the schedule runs all microbatch forwards stage by stage, then
  all backwards in reverse (F-then-B, the reference's schedule), averages
  the per-microbatch parameter gradients, and runs each stage's optimizer
  section where its parameters live. Activations cross stages as device
  transfers (ICI on hardware; GSPMD-free, placement is explicit).
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

_DEV_RE = re.compile(r"^(?:gpu|tpu|xpu|npu|cpu):(\d+)$")


def stage_of_tag(tag: str) -> Optional[int]:
    m = _DEV_RE.match(tag.strip()) if tag else None
    return int(m.group(1)) if m else None


@dataclass
class Section:
    """One stage's op list for one phase, with its variable interface."""
    stage: int
    phase: str  # 'forward' | 'backward' | 'optimize'
    ops: List = field(default_factory=list)
    # resolved at finalize():
    in_vars: List[str] = field(default_factory=list)   # read, produced elsewhere
    out_vars: List[str] = field(default_factory=list)  # produced, read elsewhere/fetched


@dataclass
class PipelineMeta:
    num_stages: int
    num_microbatches: int
    sections: List[Section]
    param_stage: Dict[str, int]          # param name -> owning stage
    grad_names: List[str]                # param-grad var names (accumulated)
    loss_name: str
    batch_feeds: List[str]               # feeds split along dim 0 per microbatch
    # microbatch interleave: "1F1B" (default; activation-bounded) or the
    # reference's "FThenB" (section_worker.cc:107 floor)
    schedule: str = "1F1B"


def _op_stage_tags(ops, num_stages: int) -> List[int]:
    """Resolve a stage for every op: explicit op_device tag, else producer
    of an input, else first consumer, else previous op's stage."""
    n = len(ops)
    stages: List[Optional[int]] = [None] * n
    produced_by: Dict[str, int] = {}
    for i, op in enumerate(ops):
        tag = op.all_attrs().get("op_device", "")
        stages[i] = stage_of_tag(tag)
        for v in op.output_arg_names():
            produced_by[v] = i

    # producer rule (forward pass over ops): an untagged op joins the
    # stage of its *latest* producer in program order — the last input to
    # become available under the per-phase section schedule, so the op's
    # section never runs before one of its producers (e.g. a cross-stage
    # grad `sum` for tied weights joins the stage whose backward runs
    # last, which is also the param's home stage)
    for i, op in enumerate(ops):
        if stages[i] is None:
            cand = [
                (produced_by[v], stages[produced_by[v]])
                for v in op.input_arg_names()
                if v in produced_by and produced_by[v] < i and stages[produced_by[v]] is not None
            ]
            if cand:
                stages[i] = max(cand)[1]
    # consumer rule (backward pass)
    consumer_stage: Dict[str, int] = {}
    for i in reversed(range(n)):
        op = ops[i]
        if stages[i] is None:
            cand = [consumer_stage[v] for v in op.output_arg_names() if v in consumer_stage]
            if cand:
                stages[i] = min(cand)
        if stages[i] is not None:
            for v in op.input_arg_names():
                consumer_stage.setdefault(v, stages[i])
    # neighbor fallback
    prev = 0
    for i in range(n):
        if stages[i] is None:
            stages[i] = prev
        prev = stages[i]
    return [min(max(s, 0), num_stages - 1) for s in stages]


def split_program(
    program,
    num_stages: int,
    n_fwd_ops: int,
    n_bwd_ops: int,
    params_grads,
    loss,
    keep_vars=(),
) -> PipelineMeta:
    """Partition block-0 ops into per-stage forward/backward/optimize
    sections and compute each section's variable interface."""
    block = program.global_block()
    ops = list(block.ops)
    feed_names = [
        v.name for v in block.vars.values() if getattr(v, "need_check_feed", False)
    ]
    stages = _op_stage_tags(ops, num_stages)

    def phase(i: int) -> str:
        if i < n_fwd_ops:
            return "forward"
        if i < n_bwd_ops:
            return "backward"
        return "optimize"

    sec_map: Dict[Tuple[str, int], Section] = {}
    order: List[Section] = []
    for i, op in enumerate(ops):
        if op.type in ("feed", "fetch"):
            continue
        key = (phase(i), stages[i])
        sec = sec_map.get(key)
        if sec is None:
            sec = Section(stage=key[1], phase=key[0])
            sec_map[key] = sec
            order.append(sec)
        sec.ops.append(op)

    # variable interface: a var is a section output if a LATER-scheduled
    # section (or the fetch set) reads it; input if produced before it
    produced_in: Dict[str, Section] = {}
    for sec in order:
        for op in sec.ops:
            for v in op.output_arg_names():
                produced_in[v] = sec

    param_stage: Dict[str, int] = {}
    for p, g in params_grads:
        # a param belongs to the stage of its first forward consumer
        for sec in order:
            if sec.phase != "forward":
                continue
            if any(p.name in op.input_arg_names() for op in sec.ops):
                param_stage[p.name] = sec.stage
                break
        else:
            param_stage[p.name] = 0

    feed_set = set(feed_names)
    for sec in order:
        seen_out: Set[str] = set()
        ins: List[str] = []
        for op in sec.ops:
            for v in op.input_arg_names():
                if v in seen_out or v in ins:
                    continue
                src = produced_in.get(v)
                if src is sec:
                    # produced earlier within this section
                    if any(v in o.output_arg_names() for o in sec.ops):
                        continue
                ins.append(v)
            for v in op.output_arg_names():
                seen_out.add(v)
        sec.in_vars = ins
        outs: List[str] = []
        for op in sec.ops:
            for v in op.output_arg_names():
                if v in outs:
                    continue
                consumed_later = any(
                    other is not sec and v in _section_reads(other)
                    for other in order
                )
                if consumed_later or v == loss.name or v in keep_vars:
                    outs.append(v)
        sec.out_vars = outs

    return PipelineMeta(
        num_stages=num_stages,
        num_microbatches=0,  # set by PipelineOptimizer
        sections=order,
        param_stage=param_stage,
        grad_names=[g.name for _, g in params_grads if g is not None],
        loss_name=loss.name,
        batch_feeds=[f for f in feed_names],
    )


def _section_reads(sec: Section) -> Set[str]:
    if not hasattr(sec, "_reads_cache"):
        r: Set[str] = set()
        prod: Set[str] = set()
        for op in sec.ops:
            for v in op.input_arg_names():
                if v not in prod:
                    r.add(v)
            prod.update(op.output_arg_names())
        sec._reads_cache = r
    return sec._reads_cache
