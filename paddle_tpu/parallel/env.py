"""Distributed environment: rank/world-size discovery.

Counterpart of the reference env-variable protocol set by
`paddle.distributed.launch` (/root/reference/python/paddle/distributed/
launch.py:71-74: PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS) — the same variables are honored, with
jax.distributed as the underlying rendezvous instead of NCCL-id broadcast.
One process per HOST (all local TPU chips belong to it), not per device.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def init_parallel_env(coordinator_address: Optional[str] = None):
    """Reference paddle.distributed.init_parallel_env (parallel.py:32).
    Single-process setups are a no-op; multi-process uses
    jax.distributed.initialize with the launch env protocol."""
    global _initialized
    if _initialized:
        return
    n = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if n > 1:
        endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        coord = coordinator_address or (endpoints[0] if endpoints and endpoints[0] else None)
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=n,
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")),
        )
    _initialized = True


def rank() -> int:
    if _initialized or "PADDLE_TRAINER_ID" in os.environ:
        return int(os.environ.get("PADDLE_TRAINER_ID", jax.process_index()))
    return 0


def world_size() -> int:
    if _initialized or "PADDLE_TRAINERS_NUM" in os.environ:
        return int(os.environ.get("PADDLE_TRAINERS_NUM", jax.process_count()))
    return 1


def get_rank() -> int:
    return rank()


def get_world_size() -> int:
    return world_size()


class ParallelEnv:
    """Reference fluid.dygraph.ParallelEnv."""

    @property
    def rank(self):
        return rank()

    @property
    def local_rank(self):
        return rank()

    @property
    def world_size(self):
        return world_size()

    @property
    def nranks(self):
        return world_size()

    @property
    def dev_id(self):
        return 0

    @property
    def device_count(self):
        return jax.local_device_count()
