"""Ring attention: context/sequence parallelism over an ICI ring.

Green-field for this framework (SURVEY.md §5.7: the reference has no
ring/context parallelism — its long-sequence story is LoD ragged tensors
and pipeline microbatching). Design follows the blockwise-attention ring
schedule (Liu et al., Ring Attention): the sequence axis is sharded over a
mesh axis; each device keeps its Q shard resident and streams K/V shards
around the ring with `lax.ppermute`, merging per-block partial attention
with the online-softmax (running max / sum) recurrence, so the full T x T
score matrix never materializes on one chip and comm overlaps compute.

Causal masking operates on *global* positions: rank r holds query rows
[r*Tq, (r+1)*Tq); the k-th ring step brings the K/V shard of rank
(r - k) mod n, giving each score block an offset-dependent mask.

Exposed as `ring_attention(q, k, v, mesh, seq_axis=...)` (a shard_map
region composable inside the GSPMD-jit executor) and as the
`ring_attention_tpu` op for program-level use.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

# shard_map moved to the jax namespace (with check_vma) after living in
# jax.experimental (with check_rep); support both so the ring runs on
# either side of the rename
try:
    from jax import shard_map as _shard_map  # jax >= 0.6-era name
    _SHARD_MAP_KW = {"check_vma": False}
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map
    _SHARD_MAP_KW = {"check_rep": False}


def _block_attn(q, k, v, bias_mask, scale):
    """One Q-shard x K-shard block: returns (unnormalized out, row max,
    row sumexp) for online-softmax merging. q,k,v: [B,H,T,D]."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if bias_mask is not None:
        s = jnp.where(bias_mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # [B,H,Tq]
    # rows fully masked (causal first blocks) produce -inf max; guard exp
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1)  # [B,H,Tq]
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o, m, l


def _merge(o1, m1, l1, o2, m2, l2):
    """Merge two partial softmax accumulators (flash-attention recurrence)."""
    m = jnp.maximum(m1, m2)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    a1 = jnp.where(jnp.isfinite(m1), jnp.exp(m1 - m_safe), 0.0)
    a2 = jnp.where(jnp.isfinite(m2), jnp.exp(m2 - m_safe), 0.0)
    o = o1 * a1[..., None] + o2 * a2[..., None]
    l = l1 * a1 + l2 * a2
    return o, m, l


def _ring_attention_local(q, k, v, axis_name: str, causal: bool, scale: float):
    """Per-shard body (runs inside shard_map). q,k,v: [B,H,Tq,D] local."""
    n = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    tq = q.shape[2]
    tk = k.shape[2]
    perm = [(i, (i + 1) % n) for i in range(n)]  # send k/v to next rank

    q_pos = rank * tq + jnp.arange(tq)  # global query rows

    def block(i, k_blk, v_blk, o, m, l):
        src = (rank - i) % n  # whose K/V shard we hold at step i
        if causal:
            k_pos = src * tk + jnp.arange(tk)
            mask = (q_pos[:, None] >= k_pos[None, :])[None, None]  # [1,1,Tq,Tk]
        else:
            mask = None
        bo, bm, bl = _block_attn(q, k_blk, v_blk, mask, scale)
        return _merge(o, m, l, bo, bm, bl)

    # step 0 is peeled so the loop permutes *before* each block — the
    # final iteration's K/V then stay put instead of making a wasted
    # shard-sized ICI round-trip after the last block
    o0 = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    m0 = jnp.full(q.shape[:3], -jnp.inf, jnp.float32)
    l0 = jnp.zeros(q.shape[:3], jnp.float32)
    o, m, l = block(0, k, v, o0, m0, l0)

    def step(i, carry):
        k_blk, v_blk, o, m, l = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        o, m, l = block(i, k_blk, v_blk, o, m, l)
        return k_blk, v_blk, o, m, l

    # static trip count → reverse-differentiable
    _, _, o, m, l = jax.lax.fori_loop(1, n, step, (k, v, o, m, l))
    l_safe = jnp.where(l > 0, l, 1.0)
    return (o / l_safe[..., None]).astype(q.dtype)


def ring_attention(
    q,
    k,
    v,
    mesh: Mesh,
    seq_axis: str = "sp",
    batch_axis: Optional[str] = "dp",
    causal: bool = True,
    scale: Optional[float] = None,
):
    """Global-view entry: q,k,v are [B,H,T,D] arrays (sharded or not);
    the sequence dim is sharded over `seq_axis` and attention runs as a
    shard_map ring. Composable under jit: the surrounding program stays
    GSPMD-partitioned while this region is manual SPMD."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    b_ax = batch_axis if batch_axis in mesh.axis_names else None
    spec = P(b_ax, None, seq_axis, None)

    fn = functools.partial(
        _ring_attention_local, axis_name=seq_axis, causal=causal, scale=scale
    )
    return _shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        **_SHARD_MAP_KW,
    )(q, k, v)
