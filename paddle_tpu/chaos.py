"""Deterministic chaos injection: named fault sites in the hot paths.

The fault plane's measurement problem is that real failures are rare and
unreproducible; a recovery path nobody can trigger on demand is a
recovery path nobody has tested. This module makes failure a first-class,
*deterministic* input: a flags-registry-gated spec
(``PADDLE_TPU_CHAOS_SITES``) arms named sites wired into the code paths
that actually fail at pod scale, and every decision derives from
``PADDLE_TPU_CHAOS_SEED`` + the site's per-process check counter — the
same spec and seed reproduce the same faults at the same points, which
is what lets tools/chaos_bench.py and the tier-1 kill-one-rank test
certify recovery instead of hoping for it.

Sites (each check is one potential injection point):

  kill_rank         hapi fit loop, at the OPEN of a global step:
                    ``os._exit`` — the SIGKILL-shaped loss of one rank
                    (params: step, rank, exit, attempt — default
                    attempt=0 fires on the FIRST elastic incarnation
                    only, so the respawned run recovers instead of
                    re-dying at the same step; -1 = every attempt)
  collective_delay  sleep before a collective payload exchange — the
                    straggler (params: ms, prob, rank, after, times)
  collective_abort  raise typed ``errors.Unavailable`` instead of the
                    exchange — the torn fabric (prob, rank, after, times)
  rpc_error         PSClient.call raises ``errors.Unavailable`` before
                    sending — the dead pserver (prob, rank, after, times)
  io_stall          sleep inside atomic journal/checkpoint writes — the
                    wedged filesystem (ms, prob, rank, after, times)

Serving sites (the serving-plane fault surface; wired into the engine
tick loop and the router dispatch path):

  replica_kill      serving engine, at the open of the armed decode
                    tick: ``os._exit`` — the SIGKILL-shaped loss of one
                    replica mid-batch, in-flight requests and KV state
                    included (params: tick, rank, exit, attempt —
                    attempt defaults to 0 like kill_rank, so a warm-
                    restarted replica serves instead of re-dying)
  decode_stall      sleep before a decode tick's device dispatch — the
                    wedged replica whose requests blow their SLO
                    (params: ms, prob, rank, after, times)
  admit_error       raise typed ``errors.Unavailable`` at engine
                    admission / router dispatch — the flaky front door
                    retry+failover must absorb (params: rate (alias of
                    prob), rank, after, times)

Spec grammar: comma-separated ``site@key=val[:key=val...]`` entries, e.g.

  PADDLE_TPU_CHAOS_SITES='kill_rank@step=5:rank=1'
  PADDLE_TPU_CHAOS_SITES='collective_delay@ms=40:prob=0.25,io_stall@ms=20'

Common params: ``rank`` (-1 = every rank), ``prob`` (0..1, default 1),
``after`` (skip the first N checks of the site), ``times`` (max fires
per process; kill_rank and collective_abort default to 1, the rest
unbounded). Unknown sites or params raise ``InvalidArgument`` at parse —
a typoed chaos spec silently injecting nothing would certify nothing.

Every fired injection is self-describing: a ``chaos_injected_total{site}``
counter increment plus a typed flight-recorder event carrying the site,
step and parameters, so a chaos run's record states what was done to it.
Disabled mode (the default, empty spec) is inert: one cached dict lookup
per check, no counters, no events — asserted by tests.
"""
from __future__ import annotations

import os
import threading
import time
import zlib
from typing import Any, Dict, Optional

from . import flags as _flags

__all__ = [
    "SITES", "parse_sites", "plan", "armed", "enabled", "fire_counts",
    "reset", "kill_rank", "delay", "abort", "rpc_error", "io_stall",
    "replica_kill", "admit_error", "KILL_EXIT_CODE",
]

KILL_EXIT_CODE = 43  # distinct from interpreter/signal codes: assertable

# site -> {param: (default, type)}; `step` None = required when the site
# is armed (a kill with no target step would fire on step 0 of every
# run, which is never what an operator means)
SITES: Dict[str, Dict[str, Any]] = {
    # attempt: the elastic attempt (PADDLE_RESTART_COUNT +
    # PADDLE_RESPAWN_COUNT) the kill is armed for. Default 0 = first
    # incarnation only — the checkpoint resume re-runs the killed step,
    # so a kill that re-fired every attempt would defeat every elastic
    # retry by construction. -1 = every attempt (the persistent-failure
    # experiment).
    "kill_rank": {"step": None, "rank": -1, "exit": KILL_EXIT_CODE,
                  "attempt": 0},
    "collective_delay": {"ms": 50.0, "prob": 1.0, "rank": -1,
                         "after": 0, "times": -1},
    "collective_abort": {"prob": 1.0, "rank": -1, "after": 0, "times": 1},
    "rpc_error": {"prob": 1.0, "rank": -1, "after": 0, "times": 1},
    "io_stall": {"ms": 50.0, "prob": 1.0, "rank": -1, "after": 0,
                 "times": -1},
    # the serving-plane sites (PR 13): tick is to replica_kill what step
    # is to kill_rank; admit_error's `rate` is the probability (alias of
    # prob — the spec grammar operators actually write)
    "replica_kill": {"tick": None, "rank": -1, "exit": KILL_EXIT_CODE,
                     "attempt": 0},
    "decode_stall": {"ms": 50.0, "prob": 1.0, "rank": -1, "after": 0,
                     "times": -1},
    "admit_error": {"rate": 1.0, "rank": -1, "after": 0, "times": -1},
}

_INT_PARAMS = ("step", "tick", "rank", "exit", "after", "times",
               "attempt")


def elastic_attempt() -> int:
    """This process's elastic incarnation: whole-set restarts plus
    per-rank respawns (the launcher exports both counts)."""
    return (int(os.environ.get("PADDLE_RESTART_COUNT", "0") or 0)
            + int(os.environ.get("PADDLE_RESPAWN_COUNT", "0") or 0))

_lock = threading.Lock()
_checks: Dict[str, int] = {}   # per-site check counter (determinism key)
_fires: Dict[str, int] = {}    # per-site fired-injection counter
_plan_cache: Optional[tuple] = None  # (raw_spec, parsed)


def _invalid(msg: str):
    from .framework import errors as _errors

    return _errors.errors.InvalidArgument(msg)


def _unavailable(msg: str):
    from .framework import errors as _errors

    return _errors.errors.Unavailable(msg)


def parse_sites(text: str) -> Dict[str, Dict[str, Any]]:
    """Parse a chaos spec into {site: params}; loud on anything unknown."""
    out: Dict[str, Dict[str, Any]] = {}
    for entry in (e.strip() for e in (text or "").split(",") if e.strip()):
        name, _, rest = entry.partition("@")
        name = name.strip()
        if name not in SITES:
            raise _invalid(
                f"PADDLE_TPU_CHAOS_SITES: unknown site {name!r} "
                f"(known: {', '.join(sorted(SITES))})")
        params = {k: v for k, v in SITES[name].items() if v is not None}
        for kv in (p for p in rest.split(":") if p.strip()):
            k, sep, v = kv.partition("=")
            k = k.strip()
            if not sep or k not in SITES[name]:
                raise _invalid(
                    f"PADDLE_TPU_CHAOS_SITES: site {name!r} does not "
                    f"take {kv.strip()!r} (params: "
                    f"{', '.join(sorted(SITES[name]))})")
            try:
                params[k] = (int(v) if k in _INT_PARAMS else float(v))
            except ValueError as e:
                raise _invalid(
                    f"PADDLE_TPU_CHAOS_SITES: {name}@{k}={v!r} is not "
                    f"a number") from e
        for k, default in SITES[name].items():
            if default is None and k not in params:
                raise _invalid(
                    f"PADDLE_TPU_CHAOS_SITES: site {name!r} requires "
                    f"{k}= (e.g. {name}@{k}=5)")
        out[name] = params
    return out


def plan() -> Dict[str, Dict[str, Any]]:
    """The armed sites, parsed from the live env (cached on the raw
    string, so monkeypatched tests re-arm and the hot-path cost of the
    disabled mode stays one string compare)."""
    global _plan_cache
    raw = str(_flags.env_flag("PADDLE_TPU_CHAOS_SITES"))
    cached = _plan_cache
    if cached is not None and cached[0] == raw:
        return cached[1]
    parsed = parse_sites(raw)
    _plan_cache = (raw, parsed)
    return parsed


def enabled() -> bool:
    return bool(plan())


def armed(site: str) -> bool:
    return site in plan()


def reset() -> None:
    """Drop per-process counters (tests)."""
    global _plan_cache
    with _lock:
        _checks.clear()
        _fires.clear()
    _plan_cache = None


def fire_counts() -> Dict[str, int]:
    with _lock:
        return dict(_fires)


def _rank() -> int:
    from . import monitor as _monitor

    return _monitor.trainer_rank()


def _uniform(seed: int, site: str, rank: int, n: int) -> float:
    """Deterministic U[0,1) for the n-th check of a site on a rank:
    crc32 over the identity tuple — stable across processes and python
    hash seeds, the property the 'same seed, same faults' contract
    needs."""
    h = zlib.crc32(f"{seed}/{site}/{rank}/{n}".encode())
    return h / 2.0 ** 32


def _record(site: str, **detail) -> None:
    """One fired injection: counter + typed flight event + one stderr
    line (the run's self-description — a chaos record must say what was
    done to it even when the process dies before any journal flush)."""
    import sys

    from . import monitor as _monitor

    _monitor.counter(
        "chaos_injected_total",
        "chaos faults fired by site", ("site",)).labels(site=site).inc()
    _monitor.flight_record("chaos", site, **detail)
    print(f"[chaos] {site} fired: "
          + " ".join(f"{k}={v}" for k, v in sorted(detail.items())),
          file=sys.stderr, flush=True)


def _decide(site: str, step: Optional[int] = None) -> Optional[Dict[str, Any]]:
    """Shared arming/decision path: returns the site params when this
    check fires, None otherwise. Bumps the check counter either way so
    probabilistic decisions stay aligned with the check sequence."""
    p = plan().get(site)
    if p is None:
        return None
    rank = _rank()
    if p.get("rank", -1) not in (-1, rank):
        return None
    if "attempt" in p and int(p["attempt"]) != -1 \
            and int(p["attempt"]) != elastic_attempt():
        return None
    # one lock window from check-count bump to fire-count bump: two
    # concurrent checks (the comms thread + the main thread) must never
    # both pass a times=1 cap — the same-spec-same-faults contract
    with _lock:
        n = _checks[site] = _checks.get(site, 0) + 1
        # `tick` is the serving sites' step: one armed scheduler tick
        for key in ("step", "tick"):
            if key in p and (step is None or int(step) != int(p[key])):
                return None
        if n <= int(p.get("after", 0)):
            return None
        times = int(p.get("times", -1))
        if times >= 0 and _fires.get(site, 0) >= times:
            return None
        prob = float(p.get("prob", p.get("rate", 1.0)))
        if prob < 1.0:
            seed = int(_flags.env_flag("PADDLE_TPU_CHAOS_SEED"))
            if _uniform(seed, site, rank, n) >= prob:
                return None
        _fires[site] = _fires.get(site, 0) + 1
    return p


# ---------------------------------------------------------------------------
# the sites
# ---------------------------------------------------------------------------


def kill_rank(step: int) -> None:
    """The fit loop's per-step check: at the armed (step, rank) the
    process dies NOW, unflushed — the honest SIGKILL shape recovery has
    to survive. ``os._exit`` skips atexit so journals and checkpoints
    hold exactly what the cadence flushes persisted, like a real crash."""
    p = _decide("kill_rank", step=step)
    if p is None:
        return
    _record("kill_rank", step=int(step), rank=_rank(),
            exit=int(p["exit"]))
    os._exit(int(p["exit"]))


def delay(site: str = "collective_delay", where: str = "") -> float:
    """Sleep at an armed delay site; returns the injected seconds."""
    p = _decide(site)
    if p is None:
        return 0.0
    secs = float(p.get("ms", 50.0)) / 1e3
    _record(site, ms=float(p.get("ms", 50.0)), where=where, rank=_rank())
    time.sleep(secs)
    return secs


def abort(site: str = "collective_abort", where: str = "") -> None:
    """Raise typed ``errors.Unavailable`` at an armed abort site — the
    injected fabric failure the coordinated-detection path must surface,
    never swallow."""
    if _decide(site) is None:
        return
    _record(site, where=where, rank=_rank())
    raise _unavailable(
        f"chaos {site} injected at {where or 'collective'} "
        f"(rank {_rank()})")


def rpc_error(method: str = "") -> None:
    """PS client site: the armed call dies before any bytes move."""
    if _decide("rpc_error") is None:
        return
    _record("rpc_error", method=method, rank=_rank())
    raise _unavailable(
        f"chaos rpc_error injected before rpc/{method} (rank {_rank()})")


def replica_kill(tick: int) -> None:
    """The serving engine's per-decode-tick check: at the armed
    (tick, rank) the replica process dies NOW — in-flight requests, KV
    state and unflushed ledger ticks all lost, the honest shape the
    router's failover and the warm-restart path have to survive."""
    p = _decide("replica_kill", step=tick)
    if p is None:
        return
    _record("replica_kill", tick=int(tick), rank=_rank(),
            exit=int(p["exit"]))
    os._exit(int(p["exit"]))


def admit_error(where: str = "") -> None:
    """Serving admission / router dispatch site: the armed check raises
    typed ``errors.Unavailable`` — the flaky front door the retry path
    must absorb (the engine fails the one request, never the batch)."""
    if _decide("admit_error") is None:
        return
    _record("admit_error", where=where, rank=_rank())
    raise _unavailable(
        f"chaos admit_error injected at {where or 'admission'} "
        f"(rank {_rank()})")


def io_stall(path: str = "") -> float:
    """Checkpoint/journal write site: the wedged disk. Sleeps; the write
    itself still completes (a stall, not a loss)."""
    p = _decide("io_stall")
    if p is None:
        return 0.0
    secs = float(p.get("ms", 50.0)) / 1e3
    _record("io_stall", ms=float(p.get("ms", 50.0)),
            path=os.path.basename(path), rank=_rank())
    time.sleep(secs)
    return secs
