"""Runtime stat registry.

Counterpart of /root/reference/paddle/fluid/platform/monitor.h:76
(StatRegistry + STAT_ADD/STAT_RESET macros, used for GPU memory gauges):
named int/float gauges any subsystem can bump, snapshotted for
observability. The executor records per-program compile counts and the
DataLoader its queue depth through this registry.
"""
from __future__ import annotations

import threading
from typing import Dict

_LOCK = threading.Lock()
_STATS: Dict[str, float] = {}


def stat_add(name: str, value: float = 1.0) -> None:
    with _LOCK:
        _STATS[name] = _STATS.get(name, 0.0) + value


def stat_set(name: str, value: float) -> None:
    with _LOCK:
        _STATS[name] = float(value)


def stat_get(name: str) -> float:
    with _LOCK:
        return _STATS.get(name, 0.0)


def stat_reset(name: str = None) -> None:
    with _LOCK:
        if name is None:
            _STATS.clear()
        else:
            _STATS.pop(name, None)


def stats() -> Dict[str, float]:
    with _LOCK:
        return dict(_STATS)
