"""Runtime telemetry: typed metrics registry + legacy stat gauges.

Counterpart of /root/reference/paddle/fluid/platform/monitor.h:76
(StatRegistry + STAT_ADD/STAT_RESET macros, used for GPU memory gauges),
grown into the framework's observability spine: Counter / Gauge /
Histogram metric families with labels, thread-safe, near-zero cost when
disabled, exported as Prometheus text or a JSON snapshot. Every hot
subsystem reports here — the executor (compile/run latency, cache
hit/miss), the PS RPC client+server (request count/latency/bytes), the
collectives (calls/payload bytes), the DataLoader (queue depth, wait
time) and the hapi fit loop (step time, throughput) — so one snapshot
answers "where does step time go" without ad-hoc benchmarks.

Env knobs:
  PADDLE_TPU_METRICS=0        disable all recording (inc/set/observe
                              become a single bool check)
  PADDLE_TPU_METRICS_PATH=f   bench.py writes the JSON snapshot to f

The legacy ``stat_add/stat_set/stat_get/stat_reset/stats`` gauge dict is
kept verbatim (reference STAT_* macro parity); its values ride along in
both exporters.
"""
from __future__ import annotations

import bisect
import json
import os
import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "default_registry", "counter", "gauge", "histogram",
    "enabled", "enable", "snapshot", "to_prometheus", "write_snapshot",
    "reset_metrics",
    "stat_add", "stat_set", "stat_get", "stat_reset", "stats",
]

# ---------------------------------------------------------------------------
# enable switch (module-level bool: the whole disabled-mode cost)
# ---------------------------------------------------------------------------

_ENABLED = os.environ.get("PADDLE_TPU_METRICS", "1").lower() not in (
    "0", "false", "off")


def enabled() -> bool:
    return _ENABLED


def enable(flag: bool = True) -> None:
    global _ENABLED
    _ENABLED = bool(flag)


# ---------------------------------------------------------------------------
# metric families
# ---------------------------------------------------------------------------

# latency-oriented default buckets (seconds), bounded at 18 + overflow
DEFAULT_BUCKETS: Tuple[float, ...] = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _sanitize(name: str) -> str:
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return out if _NAME_RE.match(out) else "_" + out


class _Metric:
    """Family base: owns the label-keyed children and the family lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        from .framework import errors as _errors

        if not _NAME_RE.match(name):
            raise _errors.errors.InvalidArgument(
                f"metric name {name!r} is not a valid identifier")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        self._nolabel = None  # cached () child: the unlabeled fast path

    def labels(self, *values, **kv):
        """Child for one label-value combination (prometheus_client idiom:
        ``m.labels(method="pull").inc()``). Children are cached — hold the
        returned handle on hot paths to skip the lookup entirely."""
        if kv:
            try:
                values = tuple(str(kv[n]) for n in self.labelnames)
            except KeyError as e:
                from .framework import errors as _errors

                raise _errors.errors.InvalidArgument(
                    f"metric {self.name!r} labels {self.labelnames} "
                    f"got {sorted(kv)}") from e
        else:
            values = tuple(str(v) for v in values)
        # lock-free hit path (GIL-atomic dict read); lock only to create
        child = self._children.get(values)
        if child is not None:
            return child
        if len(values) != len(self.labelnames):
            from .framework import errors as _errors

            raise _errors.errors.InvalidArgument(
                f"metric {self.name!r} expects {len(self.labelnames)} "
                f"label values, got {len(values)}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = self._new_child(values)
            return child

    def _unlabeled(self):
        child = self._nolabel
        if child is None:
            child = self._nolabel = self.labels()
        return child

    def _new_child(self, values):
        raise NotImplementedError

    def _series(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return list(self._children.items())

    def _reset(self) -> None:
        # zero in place instead of dropping children: handles cached by
        # instrumentation sites stay live across reset_metrics()
        with self._lock:
            for child in self._children.values():
                child._zero()


class _ValueChild:
    __slots__ = ("_lock", "value")

    def __init__(self, lock):
        self._lock = lock
        self.value = 0.0

    def _zero(self):
        self.value = 0.0


class _CounterChild(_ValueChild):
    def inc(self, value: float = 1.0) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self.value += value


class Counter(_Metric):
    """Monotonically increasing count (requests, bytes, cache hits)."""

    kind = "counter"

    def _new_child(self, values):
        return _CounterChild(self._lock)

    def inc(self, value: float = 1.0) -> None:
        if not _ENABLED:
            return
        self._unlabeled().inc(value)

    @property
    def value(self) -> float:
        return self._unlabeled().value


class _GaugeChild(_ValueChild):
    def set(self, value: float) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self.value = float(value)

    def inc(self, value: float = 1.0) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self.value += value

    def dec(self, value: float = 1.0) -> None:
        self.inc(-value)


class Gauge(_Metric):
    """Point-in-time level (queue depth, cache size, throughput)."""

    kind = "gauge"

    def _new_child(self, values):
        return _GaugeChild(self._lock)

    def set(self, value: float) -> None:
        if not _ENABLED:
            return
        self._unlabeled().set(value)

    def inc(self, value: float = 1.0) -> None:
        if not _ENABLED:
            return
        self._unlabeled().inc(value)

    def dec(self, value: float = 1.0) -> None:
        self.inc(-value)

    @property
    def value(self) -> float:
        return self._unlabeled().value


class _HistogramChild:
    __slots__ = ("_lock", "_bounds", "counts", "sum", "count")

    def __init__(self, lock, bounds):
        self._lock = lock
        self._bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # +1: +Inf overflow
        self.sum = 0.0
        self.count = 0

    def _zero(self):
        self.counts = [0] * len(self.counts)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        if not _ENABLED:
            return
        i = bisect.bisect_left(self._bounds, value)
        with self._lock:
            self.counts[i] += 1
            self.sum += value
            self.count += 1


class Histogram(_Metric):
    """Bounded-bucket distribution (latencies). Cumulative on export, raw
    per-bucket counts internally (one bisect + int increment per observe)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help, labelnames)
        bs = tuple(sorted(buckets)) if buckets else DEFAULT_BUCKETS
        from .framework import errors as _errors

        if not bs:
            raise _errors.errors.InvalidArgument(
                f"histogram {name!r} needs at least one bucket bound")
        self.buckets = bs

    def _new_child(self, values):
        return _HistogramChild(self._lock, self.buckets)

    def observe(self, value: float) -> None:
        if not _ENABLED:
            return
        self._unlabeled().observe(value)

    def time(self):
        """Context manager observing the elapsed seconds of the block."""
        return _Timer(self)


class _Timer:
    __slots__ = ("_sink", "_t0")

    def __init__(self, sink):
        self._sink = sink

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._sink.observe(time.perf_counter() - self._t0)
        return False


# ---------------------------------------------------------------------------
# registry + exporters
# ---------------------------------------------------------------------------


class MetricsRegistry:
    """Named metric families; get-or-create with type/label checking."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> _Metric:
        from .framework import errors as _errors

        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(
                    name, help=help, labelnames=labelnames, **kw)
            elif not isinstance(m, cls) or m.labelnames != tuple(labelnames):
                raise _errors.errors.AlreadyExists(
                    f"metric {name!r} already registered as {m.kind} "
                    f"with labels {m.labelnames}")
            elif (kw.get("buckets") is not None
                    and tuple(sorted(kw["buckets"])) != m.buckets):
                raise _errors.errors.AlreadyExists(
                    f"histogram {name!r} already registered with buckets "
                    f"{m.buckets}")
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def reset(self) -> None:
        """Drop every recorded series (families stay registered)."""
        with self._lock:
            families = list(self._metrics.values())
        for m in families:
            m._reset()

    # -- exporters ------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-able view: every family with its per-label-set series,
        plus the legacy stat gauges."""
        out: Dict[str, dict] = {}
        with self._lock:
            families = list(self._metrics.values())
        for m in families:
            series = []
            for values, child in m._series():
                labels = dict(zip(m.labelnames, values))
                if m.kind == "histogram":
                    series.append({
                        "labels": labels,
                        "buckets": list(m.buckets),
                        "counts": list(child.counts),
                        "sum": child.sum,
                        "count": child.count,
                    })
                else:
                    series.append({"labels": labels, "value": child.value})
            out[m.name] = {
                "type": m.kind,
                "help": m.help,
                "series": series,
            }
        return {
            "schema": "paddle_tpu.metrics/1",
            "time_unix": time.time(),
            "metrics": out,
            "stats": stats(),
        }

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (# HELP / # TYPE + samples);
        histograms expand to cumulative _bucket/_sum/_count samples."""
        lines: List[str] = []

        def esc(v: str) -> str:
            return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                    .replace("\n", "\\n"))

        def fmt_labels(labels: Dict[str, str], extra: str = "") -> str:
            items = [f'{k}="{esc(v)}"' for k, v in labels.items()]
            if extra:
                items.append(extra)
            return "{" + ",".join(items) + "}" if items else ""

        with self._lock:
            families = list(self._metrics.values())
        for m in families:
            if m.help:
                help_text = m.help.replace("\\", "\\\\").replace("\n", "\\n")
                lines.append(f"# HELP {m.name} {help_text}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for values, child in m._series():
                labels = dict(zip(m.labelnames, values))
                if m.kind == "histogram":
                    cum = 0
                    for bound, c in zip(m.buckets, child.counts):
                        cum += c
                        le = 'le="%s"' % bound
                        lines.append(
                            f"{m.name}_bucket{fmt_labels(labels, le)} {cum}")
                    cum += child.counts[-1]
                    le_inf = 'le="+Inf"'
                    lines.append(
                        f"{m.name}_bucket{fmt_labels(labels, le_inf)} {cum}")
                    lines.append(
                        f"{m.name}_sum{fmt_labels(labels)} {child.sum}")
                    lines.append(
                        f"{m.name}_count{fmt_labels(labels)} {child.count}")
                else:
                    lines.append(
                        f"{m.name}{fmt_labels(labels)} {child.value}")
        for name, value in sorted(stats().items()):
            sname = _sanitize(name)
            lines.append(f"# TYPE {sname} gauge")
            lines.append(f"{sname} {value}")
        return "\n".join(lines) + "\n"


_default_registry = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default_registry


def counter(name: str, help: str = "",
            labelnames: Sequence[str] = ()) -> Counter:
    return _default_registry.counter(name, help, labelnames)


def gauge(name: str, help: str = "",
          labelnames: Sequence[str] = ()) -> Gauge:
    return _default_registry.gauge(name, help, labelnames)


def histogram(name: str, help: str = "", labelnames: Sequence[str] = (),
              buckets: Optional[Sequence[float]] = None) -> Histogram:
    return _default_registry.histogram(name, help, labelnames, buckets)


def snapshot() -> dict:
    return _default_registry.snapshot()


def to_prometheus() -> str:
    return _default_registry.to_prometheus()


def reset_metrics() -> None:
    _default_registry.reset()


def write_snapshot(path: str, fmt: str = "json") -> str:
    """Dump the default registry to `path` as JSON ('json') or Prometheus
    text ('prom'); returns the path."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        if fmt == "prom":
            f.write(to_prometheus())
        else:
            json.dump(snapshot(), f, indent=1)
    return path


# ---------------------------------------------------------------------------
# legacy stat gauges (reference STAT_ADD/STAT_RESET macro parity)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_STATS: Dict[str, float] = {}


def stat_add(name: str, value: float = 1.0) -> None:
    if not _ENABLED:
        return
    with _LOCK:
        _STATS[name] = _STATS.get(name, 0.0) + value


def stat_set(name: str, value: float) -> None:
    if not _ENABLED:
        return
    with _LOCK:
        _STATS[name] = float(value)


def stat_get(name: str) -> float:
    with _LOCK:
        return _STATS.get(name, 0.0)


def stat_reset(name: str = None) -> None:
    with _LOCK:
        if name is None:
            _STATS.clear()
        else:
            _STATS.pop(name, None)


def stats() -> Dict[str, float]:
    with _LOCK:
        return dict(_STATS)
