"""Runtime telemetry: typed metrics registry + legacy stat gauges.

Counterpart of /root/reference/paddle/fluid/platform/monitor.h:76
(StatRegistry + STAT_ADD/STAT_RESET macros, used for GPU memory gauges),
grown into the framework's observability spine: Counter / Gauge /
Histogram metric families with labels, thread-safe, near-zero cost when
disabled, exported as Prometheus text or a JSON snapshot. Every hot
subsystem reports here — the executor (compile/run latency, cache
hit/miss), the PS RPC client+server (request count/latency/bytes), the
collectives (calls/payload bytes), the DataLoader (queue depth, wait
time) and the hapi fit loop (step time, throughput) — so one snapshot
answers "where does step time go" without ad-hoc benchmarks.

Env knobs (declared in paddle_tpu/flags.py, the PADDLE_TPU_* registry):
  PADDLE_TPU_METRICS=0        disable all recording (inc/set/observe
                              become a single bool check)
  PADDLE_TPU_METRICS_PATH=f   bench.py writes the JSON snapshot to f
  PADDLE_TPU_TRACE_DIR=d      enable the flight recorder; dumps land in d
  PADDLE_TPU_WATCHDOG_SECS=n  start the hang watchdog: no step progress
                              for n seconds -> flight-recorder dump
  PADDLE_TPU_FLIGHT_CAPACITY  ring-buffer size (default 512 events)

The legacy ``stat_add/stat_set/stat_get/stat_reset/stats`` gauge dict is
kept verbatim (reference STAT_* macro parity); its values ride along in
both exporters.

Flight recorder (the "what was each rank doing" half of hang diagnosis,
grown from the reference heart_beat_monitor.h liveness-only design): a
bounded ring buffer of recent span/metric/progress events per process,
dumped together with all-thread stacks to PADDLE_TPU_TRACE_DIR on
SIGTERM/SIGUSR1 or when the watchdog sees no step progress for N
seconds. distributed/launch.py collects the dumps when it reaps a
dead or stale rank.
"""
from __future__ import annotations

import bisect
import collections
import itertools
import json
import os
import re
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import flags as _flags

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "default_registry", "counter", "gauge", "histogram",
    "enabled", "enable", "snapshot", "to_prometheus", "write_snapshot",
    "reset_metrics",
    "stat_add", "stat_set", "stat_get", "stat_reset", "stats",
    "trainer_rank", "set_trainer_rank", "atomic_write_text",
    "FlightRecorder", "enable_flight_recorder", "flight_recorder",
    "flight_record", "note_progress", "progress_count",
    "dump_flight_record", "install_dump_handlers",
    "start_watchdog", "stop_watchdog",
]

# ---------------------------------------------------------------------------
# enable switch (module-level bool: the whole disabled-mode cost)
# ---------------------------------------------------------------------------

# declared in flags.py (the PADDLE_TPU_* env registry); read once at import
_ENABLED = bool(_flags.env_flag("PADDLE_TPU_METRICS"))


def enabled() -> bool:
    return _ENABLED


def enable(flag: bool = True) -> None:
    global _ENABLED
    _ENABLED = bool(flag)


# ---------------------------------------------------------------------------
# metric families
# ---------------------------------------------------------------------------

# latency-oriented default buckets (seconds), bounded at 18 + overflow
DEFAULT_BUCKETS: Tuple[float, ...] = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _sanitize(name: str) -> str:
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return out if _NAME_RE.match(out) else "_" + out


class _Metric:
    """Family base: owns the label-keyed children and the family lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        from .framework import errors as _errors

        if not _NAME_RE.match(name):
            raise _errors.errors.InvalidArgument(
                f"metric name {name!r} is not a valid identifier")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        self._nolabel = None  # cached () child: the unlabeled fast path

    def labels(self, *values, **kv):
        """Child for one label-value combination (prometheus_client idiom:
        ``m.labels(method="pull").inc()``). Children are cached — hold the
        returned handle on hot paths to skip the lookup entirely."""
        if kv:
            try:
                values = tuple(str(kv[n]) for n in self.labelnames)
            except KeyError as e:
                from .framework import errors as _errors

                raise _errors.errors.InvalidArgument(
                    f"metric {self.name!r} labels {self.labelnames} "
                    f"got {sorted(kv)}") from e
        else:
            values = tuple(str(v) for v in values)
        # lock-free hit path (GIL-atomic dict read); lock only to create
        child = self._children.get(values)
        if child is not None:
            return child
        if len(values) != len(self.labelnames):
            from .framework import errors as _errors

            raise _errors.errors.InvalidArgument(
                f"metric {self.name!r} expects {len(self.labelnames)} "
                f"label values, got {len(values)}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = self._new_child(values)
            return child

    def _unlabeled(self):
        child = self._nolabel
        if child is None:
            child = self._nolabel = self.labels()
        return child

    def _new_child(self, values):
        raise NotImplementedError

    def _series(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return list(self._children.items())

    def _reset(self) -> None:
        # zero in place instead of dropping children: handles cached by
        # instrumentation sites stay live across reset_metrics()
        with self._lock:
            for child in self._children.values():
                child._zero()


class _ValueChild:
    __slots__ = ("_lock", "value")

    def __init__(self, lock):
        self._lock = lock
        self.value = 0.0

    def _zero(self):
        self.value = 0.0


class _CounterChild(_ValueChild):
    def inc(self, value: float = 1.0) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self.value += value


class Counter(_Metric):
    """Monotonically increasing count (requests, bytes, cache hits)."""

    kind = "counter"

    def _new_child(self, values):
        return _CounterChild(self._lock)

    def inc(self, value: float = 1.0) -> None:
        if not _ENABLED:
            return
        self._unlabeled().inc(value)

    @property
    def value(self) -> float:
        return self._unlabeled().value


class _GaugeChild(_ValueChild):
    def set(self, value: float) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self.value = float(value)

    def inc(self, value: float = 1.0) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self.value += value

    def dec(self, value: float = 1.0) -> None:
        self.inc(-value)


class Gauge(_Metric):
    """Point-in-time level (queue depth, cache size, throughput)."""

    kind = "gauge"

    def _new_child(self, values):
        return _GaugeChild(self._lock)

    def set(self, value: float) -> None:
        if not _ENABLED:
            return
        self._unlabeled().set(value)

    def inc(self, value: float = 1.0) -> None:
        if not _ENABLED:
            return
        self._unlabeled().inc(value)

    def dec(self, value: float = 1.0) -> None:
        self.inc(-value)

    @property
    def value(self) -> float:
        return self._unlabeled().value


class _HistogramChild:
    __slots__ = ("_lock", "_bounds", "counts", "sum", "count")

    def __init__(self, lock, bounds):
        self._lock = lock
        self._bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # +1: +Inf overflow
        self.sum = 0.0
        self.count = 0

    def _zero(self):
        self.counts = [0] * len(self.counts)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        if not _ENABLED:
            return
        i = bisect.bisect_left(self._bounds, value)
        with self._lock:
            self.counts[i] += 1
            self.sum += value
            self.count += 1


class Histogram(_Metric):
    """Bounded-bucket distribution (latencies). Cumulative on export, raw
    per-bucket counts internally (one bisect + int increment per observe)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help, labelnames)
        bs = tuple(sorted(buckets)) if buckets else DEFAULT_BUCKETS
        from .framework import errors as _errors

        if not bs:
            raise _errors.errors.InvalidArgument(
                f"histogram {name!r} needs at least one bucket bound")
        self.buckets = bs

    def _new_child(self, values):
        return _HistogramChild(self._lock, self.buckets)

    def observe(self, value: float) -> None:
        if not _ENABLED:
            return
        self._unlabeled().observe(value)

    def time(self):
        """Context manager observing the elapsed seconds of the block."""
        return _Timer(self)


class _Timer:
    __slots__ = ("_sink", "_t0")

    def __init__(self, sink):
        self._sink = sink

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._sink.observe(time.perf_counter() - self._t0)
        return False


# ---------------------------------------------------------------------------
# registry + exporters
# ---------------------------------------------------------------------------


class MetricsRegistry:
    """Named metric families; get-or-create with type/label checking."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> _Metric:
        from .framework import errors as _errors

        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(
                    name, help=help, labelnames=labelnames, **kw)
            elif not isinstance(m, cls) or m.labelnames != tuple(labelnames):
                raise _errors.errors.AlreadyExists(
                    f"metric {name!r} already registered as {m.kind} "
                    f"with labels {m.labelnames}")
            elif (kw.get("buckets") is not None
                    and tuple(sorted(kw["buckets"])) != m.buckets):
                raise _errors.errors.AlreadyExists(
                    f"histogram {name!r} already registered with buckets "
                    f"{m.buckets}")
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def reset(self) -> None:
        """Drop every recorded series (families stay registered)."""
        with self._lock:
            families = list(self._metrics.values())
        for m in families:
            m._reset()

    # -- exporters ------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-able view: every family with its per-label-set series,
        plus the legacy stat gauges."""
        out: Dict[str, dict] = {}
        with self._lock:
            families = list(self._metrics.values())
        for m in families:
            series = []
            for values, child in m._series():
                labels = dict(zip(m.labelnames, values))
                if m.kind == "histogram":
                    series.append({
                        "labels": labels,
                        "buckets": list(m.buckets),
                        "counts": list(child.counts),
                        "sum": child.sum,
                        "count": child.count,
                    })
                else:
                    series.append({"labels": labels, "value": child.value})
            out[m.name] = {
                "type": m.kind,
                "help": m.help,
                "series": series,
            }
        return {
            "schema": "paddle_tpu.metrics/1",
            "time_unix": time.time(),
            "metrics": out,
            "stats": stats(),
        }

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (# HELP / # TYPE + samples);
        histograms expand to cumulative _bucket/_sum/_count samples."""
        lines: List[str] = []

        def esc(v: str) -> str:
            return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                    .replace("\n", "\\n"))

        def fmt_labels(labels: Dict[str, str], extra: str = "") -> str:
            items = [f'{k}="{esc(v)}"' for k, v in labels.items()]
            if extra:
                items.append(extra)
            return "{" + ",".join(items) + "}" if items else ""

        with self._lock:
            families = list(self._metrics.values())
        for m in families:
            if m.help:
                help_text = m.help.replace("\\", "\\\\").replace("\n", "\\n")
                lines.append(f"# HELP {m.name} {help_text}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for values, child in m._series():
                labels = dict(zip(m.labelnames, values))
                if m.kind == "histogram":
                    cum = 0
                    for bound, c in zip(m.buckets, child.counts):
                        cum += c
                        le = 'le="%s"' % bound
                        lines.append(
                            f"{m.name}_bucket{fmt_labels(labels, le)} {cum}")
                    cum += child.counts[-1]
                    le_inf = 'le="+Inf"'
                    lines.append(
                        f"{m.name}_bucket{fmt_labels(labels, le_inf)} {cum}")
                    lines.append(
                        f"{m.name}_sum{fmt_labels(labels)} {child.sum}")
                    lines.append(
                        f"{m.name}_count{fmt_labels(labels)} {child.count}")
                else:
                    lines.append(
                        f"{m.name}{fmt_labels(labels)} {child.value}")
        for name, value in sorted(stats().items()):
            sname = _sanitize(name)
            lines.append(f"# TYPE {sname} gauge")
            lines.append(f"{sname} {value}")
        return "\n".join(lines) + "\n"


_default_registry = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default_registry


def counter(name: str, help: str = "",
            labelnames: Sequence[str] = ()) -> Counter:
    return _default_registry.counter(name, help, labelnames)


def gauge(name: str, help: str = "",
          labelnames: Sequence[str] = ()) -> Gauge:
    return _default_registry.gauge(name, help, labelnames)


def histogram(name: str, help: str = "", labelnames: Sequence[str] = (),
              buckets: Optional[Sequence[float]] = None) -> Histogram:
    return _default_registry.histogram(name, help, labelnames, buckets)


def snapshot() -> dict:
    return _default_registry.snapshot()


def to_prometheus() -> str:
    return _default_registry.to_prometheus()


def reset_metrics() -> None:
    _default_registry.reset()


def atomic_write(path: str, data) -> str:
    """Write str or bytes to `path` via a same-directory temp file +
    os.replace, so a concurrent reader (the status server, an external
    scraper, a tool tailing the file) can never observe a torn write.
    The ONE atomicity implementation — journals, snapshots and training
    checkpoints all route through it. Chaos site: an armed io_stall
    sleeps here — the wedged-disk shape every flush must survive."""
    try:  # lazy: chaos imports monitor for its counters
        from . import chaos as _chaos

        _chaos.io_stall(path)
    except ImportError:
        pass
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb" if isinstance(data, bytes) else "w") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def atomic_write_text(path: str, text: str) -> str:
    return atomic_write(path, text)


def write_snapshot(path: str, fmt: str = "json") -> str:
    """Dump the default registry to `path` as JSON ('json') or Prometheus
    text ('prom'); returns the path. Atomic (temp + rename): external
    scrapers never see a half-written snapshot."""
    text = (to_prometheus() if fmt == "prom"
            else json.dumps(snapshot(), indent=1))
    return atomic_write_text(path, text)


# ---------------------------------------------------------------------------
# legacy stat gauges (reference STAT_ADD/STAT_RESET macro parity)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_STATS: Dict[str, float] = {}


def stat_add(name: str, value: float = 1.0) -> None:
    if not _ENABLED:
        return
    with _LOCK:
        _STATS[name] = _STATS.get(name, 0.0) + value


def stat_set(name: str, value: float) -> None:
    if not _ENABLED:
        return
    with _LOCK:
        _STATS[name] = float(value)


def stat_get(name: str) -> float:
    with _LOCK:
        return _STATS.get(name, 0.0)


def stat_reset(name: str = None) -> None:
    with _LOCK:
        if name is None:
            _STATS.clear()
        else:
            _STATS.pop(name, None)


def stats() -> Dict[str, float]:
    with _LOCK:
        return dict(_STATS)


# ---------------------------------------------------------------------------
# flight recorder + hang watchdog
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded ring buffer of recent runtime events (span ends, progress
    marks, metric notes). Cheap enough to stay on during production runs;
    its whole value is the dump taken at the moment a rank dies or hangs."""

    def __init__(self, capacity: int = 512):
        self._lock = threading.Lock()
        self._events: "collections.deque" = collections.deque(maxlen=capacity)

    def record(self, kind: str, name: str, **fields) -> None:
        event = {"t": time.time(), "kind": kind, "name": name}
        if fields:
            event.update(fields)
        with self._lock:
            self._events.append(event)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


_FLIGHT: Optional[FlightRecorder] = None
_FLIGHT_DIR: Optional[str] = None
_DUMP_SEQ = itertools.count(1)
_PROGRESS = 0
_WATCHDOG: Optional["_Watchdog"] = None


def enable_flight_recorder(capacity: Optional[int] = None,
                           dir: Optional[str] = None) -> FlightRecorder:
    global _FLIGHT, _FLIGHT_DIR
    if _FLIGHT is None:
        cap = capacity or int(_flags.env_flag("PADDLE_TPU_FLIGHT_CAPACITY"))
        _FLIGHT = FlightRecorder(cap)
    elif capacity and capacity != _FLIGHT._events.maxlen:
        # resize in place, keeping recent history: the recorder may have
        # been auto-created at import (env wiring) with the default size
        with _FLIGHT._lock:
            _FLIGHT._events = collections.deque(
                _FLIGHT._events, maxlen=capacity)
    if dir:
        _FLIGHT_DIR = dir
    return _FLIGHT


def flight_recorder() -> Optional[FlightRecorder]:
    return _FLIGHT


def flight_record(kind: str, name: str, **fields) -> None:
    """Record into the flight ring iff enabled — a single None check on
    the hot path (the profiler feeds every finished span through here)."""
    fr = _FLIGHT
    if fr is not None:
        fr.record(kind, name, **fields)


def note_progress(step: Optional[int] = None) -> None:
    """Bump the per-process step-progress counter the watchdog monitors.
    Called by Executor.run and the hapi fit loop once per step."""
    global _PROGRESS
    _PROGRESS += 1
    fr = _FLIGHT
    if fr is not None:
        fr.record("progress", "step", step=step)


def progress_count() -> int:
    return _PROGRESS


_RANK_OVERRIDE: Optional[int] = None


def set_trainer_rank(rank: int) -> None:
    """Override the env-derived rank (profiler.set_rank forwards here,
    so traces, journals, flight dumps and the status endpoints all agree
    on one identity)."""
    global _RANK_OVERRIDE
    changed = _RANK_OVERRIDE != int(rank)
    _RANK_OVERRIDE = int(rank)
    if changed:
        try:  # the goodput journal is rank-keyed: re-anchor its resume
            from . import goodput as _goodput

            _goodput._rank_changed()
        except Exception:
            pass
        try:  # the memwatch journal shares the rank-keyed contract
            from . import memwatch as _memwatch

            _memwatch._rank_changed()
        except Exception:
            pass
        try:  # so does the training-dynamics journal
            from . import dynamics as _dynamics

            _dynamics._rank_changed()
        except Exception:
            pass
        try:  # and the interconnect ledger journal
            from . import commswatch as _commswatch

            _commswatch._rank_changed()
        except Exception:
            pass


def trainer_rank() -> int:
    """This process's trainer rank (launch.py PADDLE_* env protocol; 0
    standalone) — the one shared resolver for journal filenames, flight
    dumps and the status endpoints."""
    if _RANK_OVERRIDE is not None:
        return _RANK_OVERRIDE
    return int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)


def _thread_stacks() -> Dict[str, List[str]]:
    """Formatted stacks of every live thread (sys._current_frames): the
    'where is each thread stuck' half of a hang dump."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for tid, frame in sys._current_frames().items():
        key = f"{names.get(tid, 'thread')}-{tid}"
        out[key] = [ln.rstrip("\n") for ln in traceback.format_stack(frame)]
    return out


def dump_flight_record(reason: str = "", path: Optional[str] = None,
                       dir: Optional[str] = None) -> str:
    """Write {reason, rank, last-N events, all-thread stacks} as JSON.
    Default location: PADDLE_TPU_TRACE_DIR/flight.rank<k>.pid<p>.<n>.json
    (sequence-numbered: one process may dump more than once)."""
    doc = {
        "schema": "paddle_tpu.flight/1",
        "reason": reason,
        "time_unix": time.time(),
        "rank": trainer_rank(),
        "pid": os.getpid(),
        "progress": _PROGRESS,
        "events": _FLIGHT.events() if _FLIGHT is not None else [],
        "stacks": _thread_stacks(),
    }
    if path is None:
        base = (dir or _FLIGHT_DIR
                or _flags.env_flag("PADDLE_TPU_TRACE_DIR") or ".")
        path = os.path.join(
            base,
            f"flight.rank{doc['rank']}.pid{doc['pid']}.{next(_DUMP_SEQ)}.json")
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    return path


def install_dump_handlers(signums: Optional[Sequence[int]] = None) -> List[int]:
    """Install signal handlers that dump the flight record. SIGUSR1 dumps
    and continues (poke a live-but-suspect rank); SIGTERM dumps and then
    re-delivers to the previous handler/default so the process still
    dies. Main-thread only (signal module restriction)."""
    import signal as _signal

    if signums is None:
        signums = [_signal.SIGTERM]
        if hasattr(_signal, "SIGUSR1"):
            signums.append(_signal.SIGUSR1)
    prev: Dict[int, object] = {}

    def _handler(signum, frame):
        try:
            dump_flight_record(reason=f"signal {signum}")
        except Exception:
            pass  # never mask the shutdown path with a dump failure
        try:
            # flush the span trace too: SIGTERM's default disposition
            # skips atexit, and the launcher-terminated rank is exactly
            # the one whose timeline the merge needs
            from . import profiler as _profiler

            _profiler.flush_trace()
        except Exception:
            pass
        if signum == _signal.SIGTERM:
            p = prev.get(signum)
            if callable(p):
                p(signum, frame)
            else:
                _signal.signal(signum, _signal.SIG_DFL)
                os.kill(os.getpid(), signum)

    installed = []
    for s in signums:
        prev[s] = _signal.signal(s, _handler)
        installed.append(int(s))
    return installed


class _Watchdog(threading.Thread):
    """Dumps the flight record when the watched progress value stalls for
    `stall_seconds`. One dump per stall episode: a new dump needs progress
    to resume and stall again first. Arms only once steps have actually
    happened (initial progress nonzero, or the first observed tick) — a
    process that never trains (pserver, a tool importing the package)
    must not be reported as hung."""

    def __init__(self, stall_seconds: float, interval: float,
                 progress_fn: Callable[[], float],
                 dir: Optional[str] = None):
        super().__init__(name="paddle-tpu-watchdog", daemon=True)
        self.stall_seconds = float(stall_seconds)
        self.interval = float(interval)
        self._progress_fn = progress_fn
        self._dir = dir
        self._stop_ev = threading.Event()
        self.dumps: List[str] = []

    def run(self):
        last_val = self._progress_fn()
        last_t = time.monotonic()
        armed = bool(last_val)
        dumped = False
        while not self._stop_ev.wait(self.interval):
            cur = self._progress_fn()
            now = time.monotonic()
            if cur != last_val:
                last_val, last_t, dumped = cur, now, False
                armed = True
            elif armed and not dumped and now - last_t >= self.stall_seconds:
                try:
                    self.dumps.append(dump_flight_record(
                        reason=(f"watchdog: no step progress for "
                                f"{now - last_t:.1f}s"),
                        dir=self._dir))
                except Exception:
                    pass
                dumped = True

    def stop(self):
        self._stop_ev.set()


def start_watchdog(stall_seconds: Optional[float] = None,
                   interval: Optional[float] = None,
                   progress_fn: Optional[Callable[[], float]] = None,
                   dir: Optional[str] = None) -> _Watchdog:
    """Start the hang watchdog. Defaults: stall from
    PADDLE_TPU_WATCHDOG_SECS (120), progress = the counter
    note_progress() bumps. A no-arg call returns any already-running
    watchdog (idempotent); explicit arguments replace it — the env
    auto-start must not silently swallow a caller's configuration."""
    global _WATCHDOG
    if _WATCHDOG is not None and _WATCHDOG.is_alive():
        if (stall_seconds is None and interval is None
                and progress_fn is None and dir is None):
            return _WATCHDOG
        stop_watchdog()
    stall = float(stall_seconds if stall_seconds is not None
                  else _flags.env_flag("PADDLE_TPU_WATCHDOG_SECS") or 120)
    enable_flight_recorder(dir=dir)
    wd = _Watchdog(
        stall,
        interval if interval is not None else max(0.05, min(1.0, stall / 4)),
        progress_fn or progress_count,
        dir=dir,
    )
    wd.start()
    _WATCHDOG = wd
    return wd


def stop_watchdog() -> None:
    global _WATCHDOG
    if _WATCHDOG is not None:
        _WATCHDOG.stop()
        _WATCHDOG = None


# env-driven wiring: launch.py exports PADDLE_TPU_TRACE_DIR (and the
# watchdog knob rides along in the inherited environment), so every
# spawned rank records flights + answers dump signals with no code change
_env_trace_dir = _flags.env_flag("PADDLE_TPU_TRACE_DIR")
if _env_trace_dir:
    enable_flight_recorder(dir=_env_trace_dir)
    try:
        install_dump_handlers()
    except (ValueError, OSError):
        pass  # non-main thread / restricted env: dumps stay on-demand
if float(_flags.env_flag("PADDLE_TPU_WATCHDOG_SECS")) > 0:
    start_watchdog()
