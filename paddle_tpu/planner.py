"""The auto-planner: observability becomes decision-making.

Every measurement layer this repo grew — ``memory_fit`` (donation-
adjusted per-device peak vs the stated HBM), ``roofline`` (compute /
HBM / ICI step estimate), the recipes' analytic comms plan
(``ResolvedRecipe.predicted_collectives``) reconciled against the
HLO-extracted one, per-axis byte attribution — existed to *describe* a
layout a human already picked via ``strategy.sharding_recipe``. This
module closes the loop the ROADMAP names (item 4, TACCL
arXiv:2111.04867, the MLPerf TPU-pod playbook arXiv:1909.09756):
given a model, a TopoSpec and an HBM budget, it

1. **enumerates** every feasible recipe layout — the named presets plus
   every axis-size factorization of the device count
   (``parallel/recipes.enumerate_layouts``);
2. **scores** each candidate through the SAME observability primitives
   a single ``tools/topo_plan.py`` plan runs (one scoring path — the
   topo_plan report is the planner's single-candidate degenerate case):
   the full train step is AOT trace->lower->compiled against abstract
   sharded inputs per layout, mined for per-device FLOPs / bytes /
   donation-adjusted peak, the comms plan per mesh axis, and a roofline
   step estimate;
3. **decides**: candidates that do not fit inside the HBM headroom
   (``PADDLE_TPU_PLAN_HEADROOM``) are rejected as ``oom``; the
   survivors rank by predicted step time; the top-K
   (``PADDLE_TPU_PLAN_TOPK``) survive with their predictions, the rest
   are rejected as ``comms-bound`` / ``worse-roofline`` — every
   rejection carries its why-not;
4. **calibrates**: committed ``MULTICHIP_r*.json`` / ``BENCH_r*.json``
   rounds are replayed through the same roofline scoring, the
   per-metric predicted-vs-measured ratio is reported, and its median
   becomes a stated correction factor that rides the plan report (the
   prediction is a model; the correction says how wrong it has been);
5. **is judged**: ``tools/mesh_bench.py --validate`` runs the pick plus
   the runners-up on the real MULTICHIP harness and records
   ``planner_regret`` = (measured step of pick - measured best) /
   measured best — a first-class perf_gate metric, lower is better.

``tools/auto_plan.py`` is the CLI; ``tools/topo_plan.py`` renders the
single-candidate case through :func:`score_candidate` below.
"""
from __future__ import annotations

import glob
import json
import os
import re
import statistics
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import flags as _flags

__all__ = [
    "MODEL_PRESETS", "PLAN_SCHEMA",
    "resolve_devices", "build_train_artifacts", "score_candidate",
    "decide", "plan", "render_plan_text",
    "load_round_history", "calibration_pairs_from_history", "calibrate",
    "link_class_bandwidth_from_history", "planner_regret",
]

PLAN_SCHEMA = "paddle_tpu.auto_plan/1"

# model presets shared by the planner CLIs: tiny (self-test / smoke),
# the bench flagship, and the mesh_bench MULTICHIP workload ("bench" —
# kept byte-identical to tools/mesh_bench.MODEL, asserted by tests, so
# a plan for the bench model scores exactly what the bench measures)
MODEL_PRESETS: Dict[str, dict] = {
    "tiny": dict(vocab_size=256, n_layer=2, n_head=4, d_model=64,
                 max_seq_len=128),
    "gpt2s": dict(vocab_size=32768, n_layer=12, n_head=12, d_model=768,
                  max_seq_len=2048),
    "bench": dict(vocab_size=2048, n_layer=4, n_head=8, d_model=256,
                  max_seq_len=128),
}

REJECT_REASONS = ("oom", "comms-bound", "worse-roofline")


# ---------------------------------------------------------------------------
# topology resolution (describe-or-degrade, shared with topo_plan)
# ---------------------------------------------------------------------------


def resolve_devices(topology: str, num_slices: int = 1,
                    probe_timeout: Optional[float] = None) -> Dict[str, Any]:
    """Resolve a topology spec string to devices, degrading a TPU spec
    that this host cannot describe to a same-count CPU mesh with the
    reason recorded. Returns ``{spec, devices, source, skip_reason,
    detail}`` — ``devices`` is None when the plan is unavailable (the
    ``skip_reason``/``detail`` then explain why)."""
    from .framework import topology as topo

    spec = topo.parse_topology(topology, num_slices=num_slices)
    devices, source = topo.describe(spec, probe_timeout=probe_timeout)
    out = {"spec": spec, "devices": devices, "source": source,
           "skip_reason": None, "detail": None}
    if devices is None and spec.platform == "tpu":
        # no TPU runtime on this host: degrade to the local CPU devices
        # (same count when possible) so the scoring path still runs —
        # the SKIP reason is part of the report, not a crash
        out["skip_reason"] = source
        import jax

        cpus = [d for d in jax.devices() if d.platform == "cpu"]
        want = spec.n_devices
        if len(cpus) >= want:
            out.update(devices=cpus[:want], source="cpu-fallback")
        else:
            out.update(source=None, detail=(
                f"and no CPU fallback: {want} devices wanted, "
                f"{len(cpus)} present"))
    elif devices is None:
        out.update(skip_reason=source, source=None)
    return out


# ---------------------------------------------------------------------------
# the train-program artifacts (built ONCE per plan, shared by every
# candidate — only the mesh/shardings differ between layouts)
# ---------------------------------------------------------------------------


class _ShapeScope:
    """Answers Executor._analyze_block's scope.has() from program var
    metadata alone — the piece that lets a plan analyze which vars the
    block reads/writes without ever materializing the state."""

    def __init__(self, names):
        self._names = set(names)

    def has(self, name: str) -> bool:
        return name in self._names


def model_config(preset, cfg_overrides: Optional[dict] = None,
                 seq: Optional[int] = None) -> Tuple[str, dict]:
    """(preset_name, cfg_kwargs) from a preset name or an explicit
    config dict; ``seq`` floors max_seq_len."""
    if isinstance(preset, dict):
        name, cfg_kwargs = "custom", dict(preset)
    else:
        name, cfg_kwargs = str(preset), dict(MODEL_PRESETS[str(preset)])
    cfg_kwargs.update(cfg_overrides or {})
    if seq:
        cfg_kwargs["max_seq_len"] = max(
            cfg_kwargs.get("max_seq_len", seq), int(seq))
    return name, cfg_kwargs


def build_train_artifacts(preset, batch: int, seq: int,
                          cfg_overrides: Optional[dict] = None
                          ) -> Dict[str, Any]:
    """Build the FULL GPT train program (forward + backward + Adam) once
    and mine the metadata every candidate scoring needs: block var
    shapes/dtypes, the scope-resident state set (read-before-write),
    feed names, parameter entries, state byte totals. ``preset`` is a
    MODEL_PRESETS name or an explicit config dict. Nothing is
    materialized — abstract values are built per candidate."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.framework import program_guard
    from paddle_tpu.framework.executor import Executor
    from paddle_tpu.models.gpt import GPTConfig, build_train_program
    from paddle_tpu.optimizer import Adam

    preset_name, cfg_kwargs = model_config(preset, cfg_overrides, seq)
    cfg = GPTConfig(**cfg_kwargs)
    # program building needs static mode; restore the caller's mode
    # after — an in-process planner must not leak static mode into a
    # dygraph session (or the test process)
    was_dygraph = paddle.in_dygraph_mode()
    paddle.enable_static()
    try:
        main, startup, io = build_train_program(cfg, batch=batch, seq=seq)
        with program_guard(main, startup):
            Adam(learning_rate=1e-4).minimize(io["loss"])
    finally:
        if was_dygraph:
            paddle.disable_static()
    block = main.global_block()

    # abstract state candidates: every block var with a concrete shape.
    # _analyze_block then decides which of them a real run would read
    # from the scope (params, moments, the lr var — anything read before
    # the block writes it); nothing is ever materialized
    state_meta: Dict[str, Tuple[Tuple[int, ...], Any]] = {}
    for name, var in block.vars.items():
        try:
            shape = tuple(int(s) for s in (var.shape or ()))
        except TypeError:
            continue
        if any(s < 0 for s in shape):
            continue
        state_meta[name] = (shape, np.dtype(var.dtype))
    feed_names = sorted({io["tokens"].name, io["labels"].name})
    scope = _ShapeScope(state_meta)
    param_names, updated_names = Executor._analyze_block(
        block, feed_names, scope)
    updated = set(updated_names)
    mutable = [n for n in param_names if n in updated]
    const = [n for n in param_names if n not in updated]

    n_params = sum(int(np.prod(state_meta[p.name][0]))
                   for p in main.all_parameters()
                   if p.name in state_meta)
    # model state = what a real run keeps resident in the scope (params,
    # optimizer moments, the lr var — _analyze_block's read-before-write
    # set), NOT every block var: feeds and temporaries are program
    # traffic, and counting them would inflate the do-I-need-FSDP number
    state_bytes = sum(
        int(np.prod(state_meta[n][0])) * state_meta[n][1].itemsize
        for n in param_names if n in state_meta)
    param_entries = [
        (p.name, state_meta[p.name][0], state_meta[p.name][1].itemsize)
        for p in main.all_parameters() if p.name in state_meta]

    return {
        "preset": preset_name, "cfg": cfg, "cfg_kwargs": cfg_kwargs,
        "main": main, "block": block, "io": io,
        "state_meta": state_meta, "feed_names": feed_names,
        "param_names": list(param_names), "mutable": mutable,
        "const": const, "loss_name": io["loss"].name,
        "batch": int(batch), "seq": int(seq),
        "n_params": int(n_params), "state_bytes": int(state_bytes),
        "n_state_vars": len(param_names), "param_entries": param_entries,
        "lm_head_impl": str(io.get("lm_head_impl", "chunked")),
    }


# ---------------------------------------------------------------------------
# per-candidate scoring — THE one memory_fit/roofline/comms pipeline
# (topo_plan's single-candidate plan and the planner's sweep both run it)
# ---------------------------------------------------------------------------


def score_candidate(artifacts: Dict[str, Any], resolved,
                    devices: Sequence[Any],
                    chip: Dict[str, float],
                    num_slices: int = 1) -> Dict[str, Any]:
    """AOT-compile the train step for one candidate layout and mine it:
    per-device cost, donation-adjusted peak, the HLO comms plan
    attributed per mesh axis, the recipe's analytic plan (attributed
    through the same ``axis_bytes_breakdown``) with its reconciliation
    verdict, and the roofline step estimate. HBM-budget-free: the fit
    verdict against a limit/headroom is :func:`decide`'s job, so one
    scoring pass serves any budget.

    The comms roofline term is priced per LINK CLASS: each axis's
    attributed bytes map to ici or dcn (``topology.axis_link_classes``
    — on a described multi-slice topology the dp axis crosses slices)
    and each class's bytes go over its own bandwidth, so a cross-slice
    candidate never prices its slow-link traffic at ICI speed. The
    chip-spec bandwidths used here are the uncalibrated baseline;
    :func:`decide` re-prices the term with a committed round's MEASURED
    per-class table when calibration carries one."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from .framework import shard_insight as shard
    from .framework import topology as topo
    from .framework.executor import lower_block
    from .framework.registry import LoweringContext
    from .models.gpt import tp_sharding_rules
    from .parallel.mesh import clean_spec, spec_for

    cfg = artifacts["cfg"]
    state_meta = artifacts["state_meta"]
    batch, seq = artifacts["batch"], artifacts["seq"]
    mesh = resolved.mesh(devices)

    # intended placement: the resolved recipe's rules (TP rules + their
    # optimizer-state variants first, first-match-wins, then the ZeRO-3
    # fsdp dim-0 catch-all — identical to what the executor applies)
    rules = resolved.sharding_rules(tp_sharding_rules(cfg))

    def _sharding_for(name: str, shape: Tuple[int, ...]):
        return NamedSharding(mesh, clean_spec(spec_for(name, rules),
                                              shape, mesh))

    def _abstract(names: List[str]) -> Dict[str, Any]:
        return {
            n: topo.abstract_value(state_meta[n][0], state_meta[n][1],
                                   _sharding_for(n, state_meta[n][0]))
            for n in names
        }

    feed_spec = resolved.batch_spec()
    feeds_abs = {
        n: topo.abstract_value((batch, seq), np.dtype("int64"),
                               NamedSharding(mesh, feed_spec))
        for n in artifacts["feed_names"]
    }
    mut_abs = _abstract(artifacts["mutable"])
    const_abs = _abstract(artifacts["const"])
    seed_abs = topo.abstract_value(
        (2,), np.dtype("uint32"), NamedSharding(mesh, PartitionSpec()))
    main, block = artifacts["main"], artifacts["block"]
    mutable, loss_name = artifacts["mutable"], artifacts["loss_name"]

    def fn(feeds, mut, const_vals, seed_step):
        rng_key = jax.random.fold_in(
            jax.random.key(seed_step[0]), seed_step[1])
        env = dict(const_vals)
        env.update(mut)
        env.update(feeds)
        ctx = LoweringContext(rng_key=rng_key, mesh=mesh)
        ctx.program = main
        # candidate layouts are scored without mutating the shared
        # program; ops that partition themselves (the pallas fused CE's
        # manual-SPMD region) read the recipe off the context so the
        # scored HLO matches what the executor will actually run
        ctx.sharding_recipe = resolved
        lower_block(ctx, block, env)
        new_state = {n: env[n] for n in mutable}
        next_seed = seed_step + jnp.asarray([0, 1], jnp.uint32)
        return env[loss_name], new_state, next_seed

    analysis = topo.aot_analyze(
        fn, (feeds_abs, mut_abs, const_abs, seed_abs), mesh=mesh,
        donate_argnums=(1, 3),
        label=f"{artifacts['preset']}@{resolved.spec}")

    comms = analysis["collectives"] or {}
    by_axis = topo.axis_bytes_breakdown(comms, mesh)

    # the recipe's ANALYTIC comms plan reconciled against what GSPMD
    # actually compiled for this layout — the same predicted-vs-measured
    # pair the MULTICHIP mesh bench gates, available AOT — and
    # attributed per mesh axis through the SAME breakdown function
    recipe_plan = resolved.predicted_collectives(
        artifacts["param_entries"], batch=batch, seq=seq,
        d_model=cfg.d_model, n_layer=cfg.n_layer,
        lmhead=artifacts.get("lm_head_impl", "chunked"))
    planned_by_axis = topo.axis_bytes_breakdown(
        {"instructions": recipe_plan.get("instructions", [])}, mesh)

    # the link-class split: every attributed axis (HLO side AND plan
    # side) maps to ici/dcn, and the roofline prices each class's bytes
    # over its own link bandwidth
    axis_classes = topo.axis_link_classes(
        sorted(set(by_axis) | set(planned_by_axis)),
        num_slices=num_slices)

    def _by_class(rows: Dict[str, dict]) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for axis, row in rows.items():
            cls = axis_classes.get(axis, "ici")
            out[cls] = out.get(cls, 0.0) + float(row["payload_bytes"])
        return out

    measured_by_class = _by_class(by_axis)
    planned_by_class = _by_class(planned_by_axis)
    roof = topo.roofline(analysis["flops"], analysis["bytes_accessed"],
                         comms.get("payload_bytes_total"), chip,
                         payload_by_link_class=measured_by_class or None)
    # the CALIBRATABLE predictor: compute + analytic-plan collectives,
    # no bytes-accessed term — the exact estimate the history replay
    # can recompute from what MULTICHIP legs record (flops + the
    # analytic plan), so a per-config correction factor learned from
    # history applies to THIS number coherently
    roof_cal = topo.roofline(analysis["flops"], None,
                             recipe_plan["payload_bytes_total"], chip,
                             payload_by_link_class=planned_by_class or None)
    plan_reconciliation = shard.license_kinds(
        shard.reconcile(recipe_plan["payload_bytes_total"],
                        measured_bytes=comms.get("payload_bytes_total", 0)),
        comms.get("by_kind"), recipe_plan["planned_kinds"])

    scored: Dict[str, Any] = {
        "spec": resolved.spec,
        "name": resolved.name,
        "axes": {str(a): int(n) for a, n in mesh.shape.items()},
        "state_bytes": artifacts["state_bytes"],
        "program": {
            "flops_per_device": analysis["flops"],
            "bytes_accessed_per_device": analysis["bytes_accessed"],
            "memory": analysis["memory"],
            "peak_bytes_per_device": analysis["peak_bytes"],
            "fit_bytes_per_device": analysis["fit_bytes"],
        },
        "comms": {
            "n_collectives": comms.get("n_collectives", 0),
            "by_kind": comms.get("by_kind", {}),
            "payload_bytes_total": comms.get("payload_bytes_total", 0),
            "comms_to_compute_bytes_per_flop": comms.get(
                "comms_to_compute_bytes_per_flop"),
            "by_axis": by_axis,
            "planned_by_axis": planned_by_axis,
            "axis_link_classes": axis_classes,
            "payload_by_link_class": measured_by_class,
            "planned_payload_by_link_class": planned_by_class,
            "recipe_plan": recipe_plan,
            "plan_reconciliation": plan_reconciliation,
        },
        "roofline": roof,
        "roofline_calibratable": roof_cal,
    }

    # sharding sanity for the largest parameter: the text grid makes a
    # mis-laid recipe visible in the report itself
    params = [p.name for p in main.all_parameters() if p.name in state_meta]
    if params:
        biggest = max(params, key=lambda n: np.prod(state_meta[n][0]))
        sds = mut_abs.get(biggest) or const_abs.get(biggest)
        if sds is not None:
            shard_desc = shard.spec_tuple(sds.sharding,
                                          len(state_meta[biggest][0]))
            scored["largest_param"] = {
                "name": biggest,
                "shape": list(state_meta[biggest][0]),
                "sharding": [list(e) if isinstance(e, tuple) else e
                             for e in shard_desc],
            }
    return scored


# ---------------------------------------------------------------------------
# the decision: feasibility, ranking, rejection reasons
# ---------------------------------------------------------------------------


def decide(scored: Sequence[Dict[str, Any]], hbm_limit_bytes: float, *,
           headroom: Optional[float] = None, top_k: Optional[int] = None,
           calibration: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Turn scored candidates into the verdict. Pure (no compilation):
    re-deciding the same scored set under a different HBM budget or
    headroom is free. Candidates whose donation-adjusted peak does not
    sit strictly inside the headroom ('fit' — 'tight' eats the slack a
    real run needs) are rejected as ``oom``; survivors rank by the
    best prediction available — the calibration-corrected calibratable
    step (per-config factor where the harness has timed this layout
    before, the global factor otherwise) when history exists, the raw
    AOT roofline when it does not; beyond the top-K the why-not is
    ``comms-bound`` (the roofline names collectives as the binding
    term) or ``worse-roofline``.

    When calibration carries a measured per-link-class bandwidth table
    (``link_class_bandwidth``, from a committed round's commswatch
    section), the calibratable step's comms term is RE-PRICED with the
    measured bytes/s before the correction factor applies — the flat
    chip-spec link term gives way to measurement, per class, so a
    dcn-heavy candidate pays its measured slow-link cost in the
    ranking."""
    from .framework import topology as topo

    if headroom is None:
        headroom = float(_flags.env_flag("PADDLE_TPU_PLAN_HEADROOM"))
    if top_k is None:
        top_k = int(_flags.env_flag("PADDLE_TPU_PLAN_TOPK"))
    top_k = max(1, int(top_k))
    cal_step = (calibration or {}).get("step_seconds") or {}
    step_factor = cal_step.get("correction_factor")
    by_config = cal_step.get("by_config") or {}
    link_bw = (calibration or {}).get("link_class_bandwidth") or {}

    def _reprice(roof_cal: Dict[str, Any]) -> Optional[float]:
        """The calibratable estimate with its comms term swapped from
        chip-spec to measured per-class bandwidth; None when no class
        of this candidate has a measurement."""
        cal_est = roof_cal.get("step_seconds_estimate")
        by_class = roof_cal.get("comms_by_link_class") or {}
        if cal_est is None or not by_class:
            return None
        if not any((link_bw.get(c) or {}).get("bus_bytes_per_sec")
                   for c in by_class):
            return None
        spec_comms = sum(r["seconds"] for r in by_class.values())
        measured_comms = 0.0
        for cls, r in by_class.items():
            bw = (link_bw.get(cls) or {}).get("bus_bytes_per_sec")
            measured_comms += (r["payload_bytes"] / bw if bw
                               else r["seconds"])
        return cal_est - spec_comms + measured_comms

    def lite(s: Dict[str, Any], fit: Dict[str, Any]) -> Dict[str, Any]:
        est = s["roofline"]["step_seconds_estimate"]
        roof_cal = s.get("roofline_calibratable") or {}
        cal_est = roof_cal.get("step_seconds_estimate")
        repriced = _reprice(roof_cal)
        per_config = (by_config.get(s["spec"]) or {}).get(
            "correction_factor")
        factor = per_config or step_factor
        base = repriced if repriced is not None else cal_est
        corrected = (base * factor
                     if base is not None and factor else None)
        rec = s["comms"]["plan_reconciliation"]
        return {
            "spec": s["spec"], "name": s["name"], "axes": s["axes"],
            "memory_fit": fit,
            "predicted": {
                "step_seconds": est,
                "step_seconds_calibratable": cal_est,
                "step_seconds_repriced": repriced,
                "step_seconds_corrected": corrected,
                "comms_pricing": ("measured" if repriced is not None
                                  else "chip_spec"),
                "correction_source": ("config" if per_config
                                      else ("global" if factor else None)),
                "peak_bytes": s["program"]["fit_bytes_per_device"],
                "raw_peak_bytes": s["program"]["peak_bytes_per_device"],
                "flops_per_device": s["program"]["flops_per_device"],
                "collective_bytes": s["comms"]["payload_bytes_total"],
                "planned_collective_bytes":
                    s["comms"]["recipe_plan"]["payload_bytes_total"],
                "bound_by": s["roofline"]["bound_by"],
            },
            "by_axis": s["comms"]["by_axis"],
            "planned_by_axis": s["comms"]["planned_by_axis"],
            "reconciliation": {"ok": rec.get("ok"),
                               "verdict": rec.get("verdict"),
                               "unplanned_kinds":
                                   rec.get("unplanned_kinds", [])},
        }

    def rank_key_value(e: Dict[str, Any]):
        p = e["predicted"]
        return (p["step_seconds_corrected"]
                if p["step_seconds_corrected"] is not None
                else p["step_seconds"])

    feasible: List[Dict[str, Any]] = []
    rejected: List[Dict[str, Any]] = []
    for s in scored:
        fit = topo.memory_fit(s["program"]["fit_bytes_per_device"],
                              hbm_limit_bytes,
                              state_bytes=s.get("state_bytes"),
                              headroom_fraction=headroom)
        entry = lite(s, fit)
        # 'fit' is feasible; 'unknown' (no memory analysis on this
        # backend) stays feasible too — rejecting what we cannot
        # measure would empty the candidate set on exactly the
        # backends that need a plan most, and the entry's memory_fit
        # carries the unknown verdict as the caveat. Only a KNOWN
        # overrun ('tight' eats the headroom a real run needs, 'oom'
        # exceeds the limit) rejects.
        if fit["verdict"] in ("fit", "unknown"):
            feasible.append(entry)
        else:
            gb = (fit.get("per_device_bytes") or 0) / 1e9
            rejected.append({
                "spec": entry["spec"], "axes": entry["axes"],
                "reason": "oom",
                "detail": (f"memory_fit={fit['verdict']}: {gb:.3f}GB "
                           f"against {hbm_limit_bytes / 1e9:.1f}GB with "
                           f"{headroom:.0%} headroom"),
                "predicted_step_seconds":
                    entry["predicted"]["step_seconds"],
                "memory_fit": fit,
            })

    # deterministic ranking on the best available prediction
    # (estimate-less candidates sink), spec string as the tie-break
    feasible.sort(key=lambda e: (
        rank_key_value(e) is None, rank_key_value(e) or 0.0, e["spec"]))
    ranked = feasible[:top_k]
    pick = ranked[0] if ranked else None
    for e in feasible[top_k:]:
        bound = e["predicted"]["bound_by"]
        reason = "comms-bound" if bound == "collective" else "worse-roofline"
        est = rank_key_value(e)
        best = rank_key_value(pick) if pick else None
        detail = (f"predicted step {est * 1e3:.3f}ms vs pick "
                  f"{best * 1e3:.3f}ms ({bound}-bound)"
                  if est is not None and best is not None
                  else f"{bound}-bound, outside top-{top_k}")
        rejected.append({
            "spec": e["spec"], "axes": e["axes"], "reason": reason,
            "detail": detail, "predicted_step_seconds": est,
            "memory_fit": e["memory_fit"],
        })

    tally: Dict[str, int] = {}
    for r in rejected:
        tally[r["reason"]] = tally.get(r["reason"], 0) + 1
    return {
        "pick": pick,
        "ranked": ranked,
        "rejected": rejected,
        "rejected_tally": dict(sorted(tally.items())),
        "n_feasible": len(feasible),
        "top_k": top_k,
        "headroom_fraction": headroom,
        "step_correction_factor": step_factor,
        "link_class_pricing": "measured" if link_bw else "chip_spec",
        "verdict": "ok" if pick is not None else "no_feasible_layout",
    }


# ---------------------------------------------------------------------------
# calibration: replaying committed history through the scoring math
# ---------------------------------------------------------------------------


_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def load_round_history(history_dir: str,
                       patterns: Sequence[str] = ("MULTICHIP_r*.json",
                                                  "BENCH_r*.json")
                       ) -> Dict[str, List[Tuple[str, dict]]]:
    """{pattern: [(round_name, doc), ...]} sorted oldest -> newest by
    the r<N> in the filename; unreadable rounds shrink the window."""
    out: Dict[str, List[Tuple[str, dict]]] = {}
    for pattern in patterns:
        rounds: List[Tuple[int, str, dict]] = []
        for path in glob.glob(os.path.join(history_dir, pattern)):
            base = os.path.basename(path)
            m = _ROUND_RE.search(base)
            if not m:
                continue
            try:
                with open(path) as f:
                    rounds.append((int(m.group(1)), base, json.load(f)))
            except (OSError, ValueError):
                continue
        out[pattern] = [(name, doc) for _, name, doc
                        in sorted(rounds, key=lambda r: r[0])]
    return out


def calibration_pairs_from_history(history: Dict[str, List[Tuple[str, dict]]],
                                   chip: Optional[Dict[str, float]] = None,
                                   link_class_bandwidth: Optional[
                                       Dict[str, dict]] = None
                                   ) -> Dict[str, List[dict]]:
    """Replay committed rounds through the same roofline/comms scoring
    the planner ranks with, pairing each prediction with the round's
    measurement:

    - MULTICHIP mesh legs: predicted step = roofline(recorded per-device
      FLOPs, recorded analytic plan bytes, the leg platform's chip
      spec) vs the measured ``step_seconds``; predicted collective
      bytes = the analytic plan total vs the HLO-extracted total.
    - BENCH rounds carrying ``step_seconds`` + ``flops_per_step``:
      the same step pairing on the 1-chip bench (older rounds without
      those fields are skipped — counted, not guessed at).

    Returns {metric: [{round, config, predicted, measured, ratio}]}
    where ratio = measured / predicted — the raw material of
    :func:`calibrate`.

    ``link_class_bandwidth`` (a committed round's measured per-class
    table) re-prices the replayed comms term the same way
    :func:`decide` will re-price candidates, so the learned correction
    factor and the measured link terms compose instead of
    double-counting. Committed mesh legs are single-slice — all-ICI —
    so only the ici entry applies here."""
    from .framework import topology as topo

    ici_bw = (link_class_bandwidth or {}).get("ici") or {}
    measured_link = ({"ici": ici_bw["bus_bytes_per_sec"]}
                     if ici_bw.get("bus_bytes_per_sec") else None)

    pairs: Dict[str, List[dict]] = {"step_seconds": [],
                                    "collective_bytes": []}

    def add(metric, rnd, config, predicted, measured):
        if not predicted or not measured or predicted <= 0 or measured <= 0:
            return
        pairs[metric].append({
            "round": rnd, "config": config,
            "predicted": round(float(predicted), 9),
            "measured": round(float(measured), 9),
            "ratio": round(float(measured) / float(predicted), 6),
        })

    for rnd, doc in history.get("MULTICHIP_r*.json", []):
        legs = ((doc.get("mesh_recipes") or {}).get("recipes")) or {}
        for name, leg in legs.items():
            if not isinstance(leg, dict):
                continue
            leg_chip = chip or topo.TPU_CHIP_SPECS.get(
                str(leg.get("platform", "cpu")), topo.TPU_CHIP_SPECS["cpu"])
            plan_total = (leg.get("predicted_collectives") or {}).get(
                "payload_bytes_total")
            roof = topo.roofline(
                leg.get("flops_per_device"), None, plan_total, leg_chip,
                payload_by_link_class=({"ici": plan_total}
                                       if plan_total and measured_link
                                       else None),
                link_bandwidth=measured_link)
            add("step_seconds", rnd, name,
                roof["step_seconds_estimate"], leg.get("step_seconds"))
            add("collective_bytes", rnd, name, plan_total,
                (leg.get("hlo_collectives") or {}).get(
                    "payload_bytes_total"))

    for rnd, doc in history.get("BENCH_r*.json", []):
        parsed = doc.get("parsed") if isinstance(doc.get("parsed"), dict) \
            else doc
        flops = parsed.get("flops_per_step")
        step = parsed.get("step_seconds")
        if flops and step:
            leg_chip = chip or topo.TPU_CHIP_SPECS["cpu"]
            roof = topo.roofline(flops, None,
                                 parsed.get("predicted_collective_bytes"),
                                 leg_chip)
            add("step_seconds", rnd, "bench",
                roof["step_seconds_estimate"], step)
    return pairs


def link_class_bandwidth_from_history(
        history: Dict[str, List[Tuple[str, dict]]],
        chip: Optional[Dict[str, float]] = None) -> Dict[str, dict]:
    """The measured per-link-class bandwidth table from the NEWEST
    committed MULTICHIP round carrying a commswatch ``comms`` section:
    {class: {bus_bytes_per_sec (the round's measured median),
    assumed_bytes_per_sec (the chip spec's term), factor_vs_spec,
    samples, round}}. This is what keeps the roofline's link terms from
    being fiction — :func:`calibrate` states it and :func:`decide`
    re-prices candidates with it. Empty when no round has measured the
    interconnect yet."""
    from .framework import topology as topo

    chip = chip or topo.TPU_CHIP_SPECS["cpu"]
    for rnd, doc in reversed(history.get("MULTICHIP_r*.json") or []):
        table = (doc.get("comms") or {}).get("link_classes") or {}
        out: Dict[str, dict] = {}
        for cls, row in sorted(table.items()):
            bw = row.get("bus_bytes_per_sec_median")
            if not bw or bw <= 0:
                continue
            assumed = (chip.get(f"{cls}_gbps") or 0.0) * 1e9
            out[cls] = {
                "bus_bytes_per_sec": float(bw),
                "assumed_bytes_per_sec": assumed or None,
                "factor_vs_spec": (round(float(bw) / assumed, 6)
                                   if assumed else None),
                "samples": row.get("samples"),
                "round": rnd,
            }
        if out:
            return out
    return {}


def calibrate(pairs: Dict[str, List[dict]],
              max_pairs_kept: int = 12,
              link_class_bandwidth: Optional[Dict[str, dict]] = None
              ) -> Dict[str, Any]:
    """Per-metric predictor calibration from replayed history pairs:
    the correction factor is the median measured/predicted ratio (what
    a prediction must be multiplied by to match this harness), and the
    errors are stated — ``raw_error`` the median |ratio - 1| before
    correction, ``residual_error`` the median relative deviation that
    REMAINS after applying the factor.

    Predictor error is not uniform across layouts (the analytic model
    is more optimistic about some recipes than others — that asymmetry
    IS the measured signal), so each metric also carries ``by_config``:
    the per-config median ratio for every config with history pairs.
    :func:`decide` ranks on the per-config-corrected calibratable
    prediction where one exists — measurements outvote the model for
    layouts the harness has already timed. An empty metric calibrates
    to factor None (predictions ride uncorrected, and the report says
    so).

    ``link_class_bandwidth`` (from
    :func:`link_class_bandwidth_from_history`) rides along under the
    ``link_class_bandwidth`` key: the per-link-class measured bus
    bandwidth + factor-vs-chip-spec that :func:`decide` re-prices the
    comms term with."""
    out: Dict[str, Any] = {}
    if link_class_bandwidth is not None:
        out["link_class_bandwidth"] = dict(link_class_bandwidth)
    for metric, rows in pairs.items():
        if not rows:
            out[metric] = {"n_pairs": 0, "correction_factor": None,
                           "raw_error": None, "residual_error": None,
                           "by_config": {}, "pairs": []}
            continue
        ratios = [r["ratio"] for r in rows]
        factor = statistics.median(ratios)
        raw = statistics.median([abs(r - 1.0) for r in ratios])
        resid = statistics.median([abs(r / factor - 1.0) for r in ratios])
        by_config: Dict[str, Any] = {}
        groups: Dict[str, List[float]] = {}
        for r in rows:
            groups.setdefault(str(r.get("config")), []).append(r["ratio"])
        for config, rs in sorted(groups.items()):
            by_config[config] = {
                "n_pairs": len(rs),
                "correction_factor": round(statistics.median(rs), 6),
            }
        out[metric] = {
            "n_pairs": len(rows),
            "correction_factor": round(factor, 6),
            "raw_error": round(raw, 4),
            "residual_error": round(resid, 4),
            "by_config": by_config,
            "pairs": rows[-max_pairs_kept:],
        }
    return out


# ---------------------------------------------------------------------------
# regret (the number the MULTICHIP validation leg gates)
# ---------------------------------------------------------------------------


def planner_regret(measured_step_seconds: Dict[str, float],
                   pick_spec: str) -> Dict[str, Any]:
    """``(measured step of pick - measured best) / measured best`` over
    a set of measured candidates that INCLUDES the pick (so regret is
    >= 0 by construction, and exactly 0 when the planner's pick is the
    measured-fastest layout)."""
    if pick_spec not in measured_step_seconds:
        raise ValueError(
            f"pick {pick_spec!r} has no measurement (have "
            f"{sorted(measured_step_seconds)})")
    bad = {k: v for k, v in measured_step_seconds.items()
           if not v or v <= 0}
    if bad:
        raise ValueError(f"non-positive measured step times: {bad}")
    best_spec = min(measured_step_seconds, key=measured_step_seconds.get)
    best = float(measured_step_seconds[best_spec])
    pick = float(measured_step_seconds[pick_spec])
    return {
        "planner_regret": round((pick - best) / best, 6),
        "measured_best": best_spec,
        "measured_best_step_seconds": round(best, 6),
        "pick_step_seconds": round(pick, 6),
    }


# ---------------------------------------------------------------------------
# the full plan (enumerate -> score -> calibrate -> decide)
# ---------------------------------------------------------------------------


def plan(topology: str, preset="tiny", batch: int = 8, seq: int = 128,
         hbm_gb: Optional[float] = None, num_slices: int = 1,
         top_k: Optional[int] = None, headroom: Optional[float] = None,
         history_dir: Optional[str] = None,
         calibration: Optional[Dict[str, Any]] = None,
         probe_timeout: Optional[float] = None,
         cfg_overrides: Optional[dict] = None,
         keep_scored: bool = False) -> Dict[str, Any]:
    """The auto-planner entry: enumerate every layout of the topology's
    device count, score each through the shared AOT pipeline, calibrate
    against committed history (``history_dir``; pass ``calibration``
    directly to reuse one), and decide. Returns the ranked plan report;
    ``keep_scored=True`` additionally carries the raw scored list so a
    caller (the self-test, a what-if) can re-:func:`decide` under a
    different budget without recompiling."""
    from .framework import topology as topo
    from .parallel import recipes as _recipes

    res = resolve_devices(topology, num_slices=num_slices,
                          probe_timeout=probe_timeout)
    spec = res["spec"]
    if res["devices"] is None:
        return {
            "schema": PLAN_SCHEMA, "available": False,
            "topology": {**spec.to_dict(), "source": None},
            "skip_reason": res["skip_reason"],
            "detail": res["detail"] or "",
        }
    devices = res["devices"]
    chip = dict(spec.chip_spec())
    if hbm_gb:
        chip["hbm_gb"] = float(hbm_gb)
    hbm_limit = chip["hbm_gb"] * (1 << 30)

    artifacts = build_train_artifacts(preset, batch, seq, cfg_overrides)
    candidates = _recipes.enumerate_layouts(len(devices))
    scored = [score_candidate(artifacts, c, devices, chip,
                              num_slices=spec.num_slices)
              for c in candidates]

    if calibration is None and history_dir:
        history = load_round_history(history_dir)
        link_bw = link_class_bandwidth_from_history(history, chip)
        calibration = calibrate(
            calibration_pairs_from_history(
                history, link_class_bandwidth=link_bw),
            link_class_bandwidth=link_bw)
    decision = decide(scored, hbm_limit, headroom=headroom, top_k=top_k,
                      calibration=calibration)

    report: Dict[str, Any] = {
        "schema": PLAN_SCHEMA,
        "available": True,
        "topology": {**spec.to_dict(), "source": res["source"],
                     "skip_reason": res["skip_reason"]},
        "model": {
            "preset": artifacts["preset"],
            "config": artifacts["cfg_kwargs"],
            "batch": artifacts["batch"], "seq": artifacts["seq"],
            "n_params": artifacts["n_params"],
            "state_bytes_total": artifacts["state_bytes"],
            "n_state_vars": artifacts["n_state_vars"],
        },
        "chip": {k: chip.get(k) for k in ("hbm_gb", "peak_flops",
                                          "hbm_gbps", "ici_gbps",
                                          "dcn_gbps")},
        "hbm_limit_bytes": int(hbm_limit),
        "n_candidates": len(scored),
        "calibration": calibration or calibrate({}),
        **decision,
    }
    if keep_scored:
        report["scored"] = scored
    return report


def render_plan_text(report: Dict[str, Any]) -> str:
    """Human-readable ranked plan (the auto_plan CLI's --format text)."""
    if not report.get("available"):
        topo_d = report.get("topology", {})
        return (f"auto_plan: UNAVAILABLE for {topo_d.get('raw')} — "
                f"{report.get('skip_reason')} {report.get('detail', '')}")
    topo_d = report["topology"]
    model = report["model"]
    lines = [
        f"== auto plan: {topo_d['raw']} ({topo_d['source']}"
        + (f", degraded: {topo_d['skip_reason']}"
           if topo_d.get("skip_reason") else "") + ") ==",
        f"model {model['preset']} batch={model['batch']} "
        f"seq={model['seq']} params={model['n_params']:,}  "
        f"hbm={report['hbm_limit_bytes'] / 2**30:.1f}GB "
        f"headroom={report['headroom_fraction']:.0%}",
        f"candidates: {report['n_candidates']} enumerated, "
        f"{report['n_feasible']} feasible, top-{report['top_k']} kept",
    ]
    cal = report.get("calibration") or {}
    link_bw = cal.get("link_class_bandwidth") or {}
    for cls, row in sorted(link_bw.items()):
        assumed = row.get("assumed_bytes_per_sec")
        factor = row.get("factor_vs_spec")
        lines.append(
            f"calibration[link:{cls}]: measured "
            f"{row['bus_bytes_per_sec'] / 1e9:.3f}GB/s bus"
            + (f" vs spec {assumed / 1e9:.1f}GB/s (x{factor:g})"
               if assumed and factor else "")
            + f" from {row.get('round')}")
    for metric, c in sorted(cal.items()):
        if metric == "link_class_bandwidth":
            continue
        if c.get("n_pairs"):
            lines.append(
                f"calibration[{metric}]: x{c['correction_factor']:g} over "
                f"{c['n_pairs']} pair(s), residual "
                f"{c['residual_error'] * 100:.1f}%")
        else:
            lines.append(f"calibration[{metric}]: no history pairs — "
                         f"predictions ride uncorrected")
    for i, e in enumerate(report["ranked"]):
        p = e["predicted"]
        star = "PICK " if i == 0 else f"  #{i + 1} "
        corrected = (f" (corrected {p['step_seconds_corrected'] * 1e3:.2f}"
                     f"ms)" if p.get("step_seconds_corrected") else "")
        lines.append(
            f"{star}{e['spec']:<16} {e['axes']}  step~"
            f"{(p['step_seconds'] or 0) * 1e3:.3f}ms{corrected} "
            f"peak={(p['peak_bytes'] or 0) / 1e6:.1f}MB "
            f"({e['memory_fit']['utilization'] * 100:.1f}%) "
            f"comms={p['collective_bytes'] / 1e6:.2f}MB "
            f"{p['bound_by']}-bound "
            f"reconcile={e['reconciliation']['verdict']}")
        for axis, row in e["by_axis"].items():
            lines.append(f"       axis {axis:<12} "
                         f"{row['payload_bytes'] / 1e6:.3f}MB "
                         f"x{row['count']}")
    for r in report["rejected"]:
        lines.append(f"  REJ {r['spec']:<16} {r['reason']:<15} "
                     f"{r['detail']}")
    lines.append(f"verdict: {report['verdict'].upper()}"
                 + (f" — pick {report['pick']['spec']}"
                    if report.get("pick") else ""))
    return "\n".join(lines)
