"""Training-dynamics observability: loss/grad telemetry + divergence judge.

PRs 1-6 made *time* (goodput) and *memory* (memwatch) observable; this
layer does the same for training *quality*. Until now the stack held two
scalar gauges (``fit_loss`` / ``fit_grad_norm``) and no trajectory: a
diverging run looked healthy on every dashboard until the operator read
the log by hand, and nothing could judge the "equal loss curves"
acceptance bar that gates quantized collectives and raw-speed rounds
(ROADMAP items 3/4; EQuARX accepts quantized all-reduce only at matched
convergence). The design deliberately mirrors goodput.py / memwatch.py:

- **per-step series**: the hapi fit loop calls :func:`feed` with each step's
  loss, global gradient norm, update-to-weight ratio and learning rate
  into the open step; :func:`end_step` (riding ``goodput.end_step``, so
  every existing step driver closes dynamics steps with no new hook)
  freezes the record into a bounded in-memory series and the per-rank
  journal.
- **fused reductions**: the global grad norm and the per-layer-prefix
  grad/weight/update norm breakdown are computed by ONE jitted device
  program over the whole tensor list (:func:`grad_health`,
  :func:`layer_breakdown`) — a single dispatch and one small host
  transfer, replacing the per-tensor host loop PR 3 ran between
  backward and step. The breakdown is sampled every
  ``PADDLE_TPU_DYNAMICS_SAMPLE`` steps.
- **anomaly detectors** (memwatch-leak style: typed counters, flight
  recorder, one stderr warning per episode): loss spike vs. EMA z-score,
  sustained divergence (EMA above its best for N steps), plateau, grad
  explosion/vanish, non-finite values.
- **journal**: per-rank ``PADDLE_TPU_DYNAMICS_DIR/dynamics.rank<k>.jsonl``
  (atomic whole-file writes: header line + one JSON line per closed
  step; restart resume; rank re-anchor via monitor.set_trainer_rank;
  the launch.py supervisor sheds persistence).
- **cross-rank desync probe**: :func:`merge_ledgers` compares final-window
  losses across ranks — under data parallelism every rank optimizes the
  same global objective, so a rank whose loss curve drifts from the
  others is a cheap, free correctness probe for broken gradient
  synchronization. launch.py prints the verdict at teardown.

The offline judge lives in ``tools/curve_gate.py``: it compares a fresh
loss trajectory (bench JSON or a dynamics journal) against the
trajectories embedded in BENCH_r*.json history, exactly the way
tools/perf_gate.py gates throughput.

Env knobs (declared in paddle_tpu/flags.py):
  PADDLE_TPU_DYNAMICS                series + detectors on/off (default on)
  PADDLE_TPU_DYNAMICS_DIR            journal directory (enables persistence)
  PADDLE_TPU_DYNAMICS_FLUSH_STEPS    journal flush cadence in steps (50)
  PADDLE_TPU_DYNAMICS_SAMPLE         per-layer breakdown cadence in steps (25)
  PADDLE_TPU_DYNAMICS_SPIKE_Z        loss-spike z-score threshold (6)
  PADDLE_TPU_DYNAMICS_DIVERGE_STEPS  sustained-divergence window (25 steps)
  PADDLE_TPU_DYNAMICS_PLATEAU_STEPS  no-improvement plateau window (200)
"""
from __future__ import annotations

import atexit
import collections
import glob
import json
import math
import os
import re
import sys
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from . import flags as _flags
from . import monitor as _monitor

__all__ = [
    "DynamicsLedger", "enabled", "ledger", "reset",
    "feed", "end_step", "totals", "summary", "status",
    "should_sample_layers", "grad_health", "layer_breakdown",
    "configure", "disable_persistence", "flush", "journal_path",
    "load_journal", "load_journals", "merge_ledgers", "check_desync",
    "render_summary", "trajectory",
    "SCHEMA", "ANOMALY_KINDS",
]

SCHEMA = "paddle_tpu.dynamics/1"

# recent closed steps kept in memory / persisted per journal rewrite.
# 4096 steps of ~120B records is ~0.5MB — cheap enough to keep whole.
_SERIES_CAP = 4096

# EMA smoothing for loss mean/variance (~ last 20 steps dominate): slow
# enough that a one-step spike stands out of the variance it feeds
_EMA_ALPHA = 0.05
# detectors stay quiet until the EMA has seen this many steps — the
# first steps of a run legitimately move fast
_WARMUP_STEPS = 20
# sustained divergence: EMA this fraction above its best-so-far counts
# as a rising step
_DIVERGE_MARGIN = 0.01
# plateau: an EMA improvement below this fraction of the best loss does
# not reset the no-progress window
_PLATEAU_MIN_DELTA = 1e-4
# gradient-norm episode thresholds (vs. the grad-norm EMA / absolute)
_GRAD_EXPLODE_FACTOR = 25.0
_GRAD_VANISH_FLOOR = 1e-10

ANOMALY_KINDS = ("loss_spike", "divergence", "plateau",
                 "grad_explode", "grad_vanish", "nonfinite")

# the dynamics metric series (mirror of the goodput/memwatch gauges)
_M_LOSS_EMA = _monitor.gauge(
    "dynamics_loss_ema", "EMA of the per-step training loss")
_M_LOSS_Z = _monitor.gauge(
    "dynamics_loss_zscore",
    "z-score of the last closed step's loss against the loss EMA/std")
_M_GRAD_EMA = _monitor.gauge(
    "dynamics_grad_norm_ema", "EMA of the global gradient norm")
_M_UPDATE_RATIO = _monitor.gauge(
    "dynamics_update_ratio",
    "last sampled update-to-weight norm ratio (lr*|grad| / |weight|)")
_M_ANOM = _monitor.counter(
    "dynamics_anomalies_total",
    "training-dynamics anomaly episodes by kind (loss_spike, divergence, "
    "plateau, grad_explode, grad_vanish, nonfinite)", ("kind",))


def enabled() -> bool:
    return _monitor.enabled() and bool(_flags.env_flag("PADDLE_TPU_DYNAMICS"))


def _spike_z() -> float:
    return float(_flags.env_flag("PADDLE_TPU_DYNAMICS_SPIKE_Z"))


def _diverge_steps() -> int:
    return max(2, int(_flags.env_flag("PADDLE_TPU_DYNAMICS_DIVERGE_STEPS")))


def _plateau_steps() -> int:
    return max(2, int(_flags.env_flag("PADDLE_TPU_DYNAMICS_PLATEAU_STEPS")))


def should_sample_layers(step: int) -> bool:
    """Is `step` a per-layer-breakdown sampling step? Every
    PADDLE_TPU_DYNAMICS_SAMPLE-th step (and step 0, so short runs still
    get at least one breakdown). 0 disables the breakdown entirely."""
    if not enabled():
        return False
    every = int(_flags.env_flag("PADDLE_TPU_DYNAMICS_SAMPLE"))
    if every <= 0:
        return False
    return int(step) % every == 0


# the staged scalar keys that may arrive lazy (device futures /
# callables) from the async-loss fit loop
_SCALAR_KEYS = ("loss", "grad_norm", "update_ratio", "lr")


def _is_lazy(v) -> bool:
    """A staged value that is not yet a host scalar: a zero-arg callable
    or a device array-like (jax future, dygraph Tensor). Host numerics
    (python / numpy scalars, numpy arrays) are never lazy."""
    import numpy as np

    if v is None or isinstance(v, (int, float, np.number, np.bool_,
                                   np.ndarray)):
        return False
    return True


def _stage_scalar(v):
    """feed() staging: host scalars are floated immediately (the
    historical behavior every sync caller keeps); lazy values pass
    through untouched so no device sync happens on the hot path."""
    return v if _is_lazy(v) else float(v)


def _force_scalar(v) -> Optional[float]:
    """Materialize a lazy scalar on the host. A failed force degrades to
    None (an absent reading) — telemetry must never kill the step."""
    import numpy as np

    try:
        if callable(v):
            v = v()
        return float(np.asarray(_as_array(v)))
    except Exception:  # noqa: BLE001
        return None


class DynamicsLedger:
    """Per-process training-dynamics ledger: the open step's staged
    telemetry, the closed-step series, EMA statistics and the anomaly
    episode state. Thread-safe; `base` holds the journal a restarted
    rank resumed from (its series prefixes this incarnation's, so the
    persisted trajectory spans restarts)."""

    def __init__(self):
        self._lock = threading.RLock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.steps = 0
            self.current_step: Optional[int] = None
            self.open: Dict[str, Any] = {}
            # one-deep finalization pipeline for lazy-fed steps (the
            # async-loss fit loop): the record whose device scalars have
            # not been forced to the host yet
            self._pending: Optional[tuple] = None
            self.last_step: Optional[dict] = None
            self.step_series: collections.deque = collections.deque(
                maxlen=_SERIES_CAP)
            self.loss_ema: Optional[float] = None
            self.loss_var = 0.0
            self.best_loss_ema: Optional[float] = None
            self.grad_ema: Optional[float] = None
            self.diverge_run = 0
            self.plateau_run = 0
            self.anomaly_counts: Dict[str, int] = {
                k: 0 for k in ANOMALY_KINDS}
            self._active: Dict[str, bool] = {k: False for k in ANOMALY_KINDS}
            self.base: Optional[dict] = None
            self.started_unix = time.time()

    # -- recording ------------------------------------------------------
    def feed(self, loss: Optional[float] = None,
             grad_norm: Optional[float] = None,
             update_ratio: Optional[float] = None,
             lr: Optional[float] = None,
             layers: Optional[Dict[str, dict]] = None) -> None:
        """Stage telemetry for the OPEN step; end_step freezes it. Only
        keys actually passed are updated, so producers at different call
        sites (loss from the fit loop, the sampled layer breakdown from
        the grads-alive window) compose into one record."""
        with self._lock:
            if loss is not None:
                self.open["loss"] = _stage_scalar(loss)
            if grad_norm is not None:
                self.open["grad_norm"] = _stage_scalar(grad_norm)
            if update_ratio is not None:
                self.open["update_ratio"] = _stage_scalar(update_ratio)
            if lr is not None:
                self.open["lr"] = _stage_scalar(lr)
            if layers is not None:
                self.open["layers"] = layers

    def _begin_episode(self, kind: str, record: dict, **fields) -> bool:
        """Count an anomaly episode once while its condition holds (the
        memwatch-leak contract). Returns True when this step STARTED the
        episode (the caller emits the one warning)."""
        if self._active[kind]:
            return False
        self._active[kind] = True
        self.anomaly_counts[kind] += 1
        record.setdefault("anomalies", []).append(
            {"kind": kind, **fields})
        return True

    def _end_episode(self, kind: str) -> None:
        self._active[kind] = False

    def end_step(self, step: Optional[int] = None,
                 spike_z: Optional[float] = None,
                 diverge_steps: Optional[int] = None,
                 plateau_steps: Optional[int] = None,
                 warmup: int = _WARMUP_STEPS) -> Optional[dict]:
        """Close the in-flight step: freeze the staged telemetry into the
        series and run every detector against the pre-update EMA stats.
        Returns the closed record (with any started anomaly episodes),
        or None when nothing was fed (an executor-only run: inert)."""
        spike_z = _spike_z() if spike_z is None else float(spike_z)
        diverge_steps = (_diverge_steps() if diverge_steps is None
                         else int(diverge_steps))
        plateau_steps = (_plateau_steps() if plateau_steps is None
                         else int(plateau_steps))
        with self._lock:
            if not self.open:
                return None
            staged, self.open = self.open, {}
            self.steps += 1
            self.current_step = (int(step) if step is not None
                                 else (self.current_step or 0) + 1)
            record: Dict[str, Any] = {
                "step": self.current_step, "t": time.time(), **staged}
            # keep the pipeline FIFO: whatever is still pending finalizes
            # before this step enters it (or before this step finalizes)
            self._drain_locked()
            args = (record, spike_z, diverge_steps, plateau_steps, warmup)
            if any(_is_lazy(record.get(k)) for k in _SCALAR_KEYS):
                # async-loss mode: the step's scalars are still device
                # futures — defer the host force, the EMAs and the
                # detectors one step so the next dispatch overlaps the
                # device finishing this one. The returned record is the
                # un-finalized shell (series/gauges update at drain).
                self._pending = args
                return record
            return self._finalize_record(*args)

    def drain(self) -> None:
        """Force the pending lazy step (if any) through finalization —
        every external view (series/totals/flush) calls this first, so
        readers never observe the one-step pipeline."""
        with self._lock:
            self._drain_locked()

    def _drain_locked(self) -> None:
        pending, self._pending = self._pending, None
        if pending is not None:
            self._finalize_record(*pending)

    def _finalize_record(self, record, spike_z, diverge_steps,
                         plateau_steps, warmup) -> dict:
        """Force any lazy scalars to host floats, then run the sanitize +
        EMA + detector pass and append to the series. Lock held."""
        for k in _SCALAR_KEYS:
            if _is_lazy(record.get(k)):
                record[k] = _force_scalar(record[k])
        staged = record
        # sanitize EVERY non-finite scalar independently (a NaN loss
        # usually comes with NaN grads): poisoned values must not
        # corrupt the EMAs, and the record must stay strict-JSON
        # (json.dumps would emit a bare NaN token that breaks /status
        # and Perfetto consumers) — the episode fields carry the
        # offending values as strings instead
        bad = {k: record[k]
               for k in ("loss", "grad_norm", "update_ratio", "lr")
               if record.get(k) is not None
               and not math.isfinite(float(record[k]))}
        for k in bad:
            record[k] = None
        loss = None if "loss" in bad else staged.get("loss")
        grad = None if "grad_norm" in bad else staged.get("grad_norm")

        if "loss" in bad or "grad_norm" in bad:
            self._begin_episode(
                "nonfinite", record,
                **{k: str(v) for k, v in bad.items()})
        else:
            self._end_episode("nonfinite")

        if loss is not None:
            if self.loss_ema is None:
                self.loss_ema = loss
                self.loss_var = 0.0
            else:
                # z-score against the PRE-update stats: the spike must
                # not dilute the mean/std it is judged against
                std = math.sqrt(max(self.loss_var, 0.0))
                floor = 1e-3 * max(1.0, abs(self.loss_ema))
                z = (loss - self.loss_ema) / max(std, floor)
                record["loss_z"] = round(z, 3)
                if self.steps > warmup and z > spike_z:
                    self._begin_episode("loss_spike", record,
                                        z=round(z, 2), loss=loss)
                else:
                    self._end_episode("loss_spike")
                delta = loss - self.loss_ema
                self.loss_ema += _EMA_ALPHA * delta
                self.loss_var = (1.0 - _EMA_ALPHA) * (
                    self.loss_var + _EMA_ALPHA * delta * delta)
            record["loss_ema"] = self.loss_ema

            # sustained divergence / plateau against the best EMA
            best = self.best_loss_ema
            if best is None:
                self.best_loss_ema = self.loss_ema
            else:
                margin = _DIVERGE_MARGIN * max(abs(best), 1e-12)
                if self.loss_ema > best + margin:
                    self.diverge_run += 1
                else:
                    self.diverge_run = 0
                    self._end_episode("divergence")
                if self.loss_ema < best - _PLATEAU_MIN_DELTA * max(
                        abs(best), 1e-12):
                    self.best_loss_ema = self.loss_ema
                    self.plateau_run = 0
                    self._end_episode("plateau")
                else:
                    self.plateau_run += 1
                if (self.steps > warmup
                        and self.diverge_run >= diverge_steps):
                    self._begin_episode(
                        "divergence", record,
                        steps=self.diverge_run,
                        loss_ema=self.loss_ema, best=best)
                if (self.steps > warmup
                        and self.plateau_run >= plateau_steps):
                    self._begin_episode(
                        "plateau", record, steps=self.plateau_run,
                        best=self.best_loss_ema)

        if grad is not None:
            if grad < _GRAD_VANISH_FLOOR:
                self._begin_episode("grad_vanish", record,
                                    grad_norm=grad)
            else:
                self._end_episode("grad_vanish")
            if self.grad_ema is None:
                self.grad_ema = grad
            else:
                if (self.steps > warmup and self.grad_ema > 0
                        and grad > _GRAD_EXPLODE_FACTOR * self.grad_ema):
                    self._begin_episode(
                        "grad_explode", record, grad_norm=grad,
                        ema=self.grad_ema)
                else:
                    self._end_episode("grad_explode")
                self.grad_ema += _EMA_ALPHA * (grad - self.grad_ema)

        self.last_step = record
        self.step_series.append(record)
        hook = self.on_finalize
        if hook is not None:
            try:
                hook(record)
            except Exception:  # noqa: BLE001 - telemetry must not kill
                pass
        return record

    # the module wires gauge/flight-record/stderr processing here so a
    # deferred (async-loss) record reports its anomalies when its values
    # actually land, not when the shell closed
    on_finalize = None

    # -- views ----------------------------------------------------------
    def series(self, limit: Optional[int] = None) -> List[dict]:
        """The recorded trajectory: resumed-journal prefix + this
        incarnation's closed steps, bounded at the series cap. `limit`
        keeps only the tail — and only copies that much, so a /status
        poll is not 4096 dict copies under the ledger lock."""
        with self._lock:
            self._drain_locked()
            live = list(self.step_series)
        full = list((self.base or {}).get("series", [])) + live
        cap = _SERIES_CAP if limit is None else max(0, int(limit))
        return [dict(s) for s in full[-cap:]] if cap else []

    def totals(self, series_limit: Optional[int] = None) -> Dict[str, Any]:
        with self._lock:
            self._drain_locked()
            steps = self.steps
            counts = dict(self.anomaly_counts)
            doc: Dict[str, Any] = {
                "schema": SCHEMA,
                "rank": _monitor.trainer_rank(),
                "pid": os.getpid(),
                "time_unix": time.time(),
                "current_step": self.current_step,
                "last_step": dict(self.last_step) if self.last_step else None,
                "loss_ema": self.loss_ema,
                "loss_std": math.sqrt(max(self.loss_var, 0.0)),
                "best_loss_ema": self.best_loss_ema,
                "grad_norm_ema": self.grad_ema,
                "active_episodes": [k for k, v in self._active.items() if v],
            }
        if self.base:
            steps += int(self.base.get("steps", 0))
            for k, v in (self.base.get("anomaly_counts") or {}).items():
                if k in counts:
                    counts[k] += int(v)
            doc["resumed_from_journal"] = True
        doc["steps"] = steps
        doc["anomaly_counts"] = counts
        doc["anomalies_total"] = sum(counts.values())
        doc["series"] = self.series(limit=series_limit)
        return doc


_LEDGER = DynamicsLedger()
_JOURNAL_DIR: Optional[str] = None
_FLUSH_STEPS = max(1, int(_flags.env_flag("PADDLE_TPU_DYNAMICS_FLUSH_STEPS")))
_steps_since_flush = 0
_atexit_registered = False


def ledger() -> DynamicsLedger:
    return _LEDGER


def reset() -> None:
    """Drop everything recorded (journal base included); tests."""
    global _steps_since_flush
    _LEDGER.reset()
    _steps_since_flush = 0


def feed(loss: Optional[float] = None, grad_norm: Optional[float] = None,
         update_ratio: Optional[float] = None, lr: Optional[float] = None,
         layers: Optional[Dict[str, dict]] = None) -> None:
    """Stage telemetry for the open step (fit loop, bench, custom
    loops). No-op when dynamics is disabled."""
    if not enabled():
        return
    _LEDGER.feed(loss=loss, grad_norm=grad_norm,
                 update_ratio=update_ratio, lr=lr, layers=layers)


def end_step(step: Optional[int] = None) -> Optional[dict]:
    """Close the dynamics step (called by goodput.end_step, so every
    step driver participates for free). Feeds the metric series, the
    flight recorder and the journal flush cadence; emits ONE stderr
    warning per started anomaly episode."""
    global _steps_since_flush
    if not enabled():
        return None
    closed = _LEDGER.end_step(step=step)
    if closed is None:
        return None
    # gauges, flight records and the one-warning-per-episode stderr line
    # run from the ledger's on_finalize hook (_post_finalize below): for
    # sync steps that already happened inside end_step; an async-loss
    # step reports when its device scalars land (the next step / drain)
    if _JOURNAL_DIR is not None:
        _steps_since_flush += 1
        if _steps_since_flush >= _FLUSH_STEPS:
            _steps_since_flush = 0
            try:
                flush()
            except OSError:
                pass  # a full disk must not kill the training loop
    return closed


def drain() -> None:
    """Finalize the async-loss pipeline's pending step (no-op
    otherwise). Drivers call this at epoch/run boundaries; every
    internal view (totals/series/flush) drains on its own."""
    _LEDGER.drain()


def _post_finalize(closed: dict) -> None:
    if closed.get("loss_ema") is not None:
        _M_LOSS_EMA.set(closed["loss_ema"])
    if closed.get("loss_z") is not None:
        _M_LOSS_Z.set(closed["loss_z"])
    if _LEDGER.grad_ema is not None:
        _M_GRAD_EMA.set(_LEDGER.grad_ema)
    if closed.get("update_ratio") is not None:
        _M_UPDATE_RATIO.set(closed["update_ratio"])
    for a in closed.get("anomalies", ()):
        _M_ANOM.labels(kind=a["kind"]).inc()
        _monitor.flight_record("dynamics", a["kind"], step=closed["step"],
                               **{k: v for k, v in a.items() if k != "kind"})
        detail = ", ".join(f"{k}={v:.4g}" if isinstance(v, float)
                           else f"{k}={v}"
                           for k, v in a.items() if k != "kind")
        print(f"[paddle_tpu.dynamics] {a['kind']} at step "
              f"{closed['step']}: {detail}", file=sys.stderr)


_LEDGER.on_finalize = _post_finalize


def totals(series_limit: Optional[int] = None) -> Dict[str, Any]:
    return _LEDGER.totals(series_limit=series_limit)


def trajectory() -> Dict[str, List[float]]:
    """The recorded loss trajectory as parallel step/loss lists — the
    candidate format tools/curve_gate.py consumes. A resumed run's step
    counter restarts at 0 (the journal prefix keeps the old numbering),
    so a non-monotonic step axis falls back to the record index — the
    interpolation in the gate requires monotonic x."""
    steps, losses = [], []
    for s in _LEDGER.series():
        if s.get("loss") is not None:
            steps.append(s["step"])
            losses.append(s["loss"])
    if any(b <= a for a, b in zip(steps, steps[1:])):
        steps = list(range(len(losses)))
    return {"steps": steps, "loss": losses}


def summary() -> Dict[str, Any]:
    doc = totals(series_limit=0)
    doc.pop("series", None)
    return doc


def status() -> Dict[str, Any]:
    """The /status `dynamics` section: EMA/anomaly state + the recent
    trajectory tail (bounded — the full series stays in the journal)."""
    doc = totals(series_limit=20)
    doc["trajectory_tail"] = doc.pop("series", [])
    return doc


# ---------------------------------------------------------------------------
# fused jitted reductions (global grad norm, per-layer breakdown)
# ---------------------------------------------------------------------------

_REDUCE_JIT = None


def _fused_norms(arrays: Sequence[Any]) -> Tuple[Any, Any]:
    """ONE jitted device program over the whole tensor list: per-tensor
    sum-of-squares (f32 accumulation) and all-finite flags, returned as
    two stacked vectors — a single dispatch and one small host transfer
    regardless of parameter count. jax caches the compilation per
    shape-set, so a fixed model costs one compile."""
    global _REDUCE_JIT
    import jax
    import jax.numpy as jnp

    if _REDUCE_JIT is None:
        def _kernel(xs):
            sq = jnp.stack([jnp.sum(jnp.square(x.astype(jnp.float32)))
                            for x in xs])
            fin = jnp.stack([jnp.all(jnp.isfinite(x.astype(jnp.float32)))
                             for x in xs])
            return sq, fin

        _REDUCE_JIT = jax.jit(_kernel)
    return _REDUCE_JIT(list(arrays))


def _as_array(value):
    """Accept dygraph Tensors, jax arrays and numpy arrays alike."""
    inner = getattr(value, "_value", None)
    return inner if inner is not None else value


def _clamp_overflow(sq):
    """f32 sum-of-squares can overflow to inf on explosion-scale grads
    whose every ELEMENT is still finite (f64 accumulation is unavailable
    under the x64-disabled JAX config this runs on). Clamp to f32-max so
    the norm stays finite-huge: the episode classifies as grad_explode —
    the truth — instead of nonfinite, and the value stays strict-JSON."""
    import numpy as np

    return np.where(np.isfinite(sq), sq, float(np.finfo(np.float32).max))


def grad_health_deferred(named_grads: Iterable[Tuple[str, Any]]):
    """Dispatch the fused grad-norm reduction NOW, pay the host transfer
    LATER: returns a memoized zero-arg callable -> (norm, bad_names).
    The async fit loop forces it one step behind, overlapping the
    device's backward with the next step's dispatch."""
    names, arrays = [], []
    for name, g in named_grads:
        if g is None:
            continue
        names.append(name)
        arrays.append(_as_array(g))
    if not arrays:
        return lambda: (0.0, [])
    sq, fin = _fused_norms(arrays)  # device dispatch only — no transfer

    cell: List[Tuple[float, List[str]]] = []

    def force() -> Tuple[float, List[str]]:
        if not cell:
            import numpy as np

            sq_h = _clamp_overflow(np.asarray(sq, dtype=np.float64))
            fin_h = np.asarray(fin, dtype=bool)
            bad = [n for n, ok in zip(names, fin_h) if not ok]
            # a non-finite square can still sum to a finite garbage value
            # on some backends; trust the explicit finite mask, not the sum
            norm = (float(np.sqrt(sq_h[fin_h].sum()))
                    if fin_h.any() else 0.0)
            cell.append((norm, bad))
        return cell[0]

    return force


def grad_health(named_grads: Iterable[Tuple[str, Any]]
                ) -> Tuple[float, List[str]]:
    """Global gradient norm + the names of non-finite gradients, via the
    fused reduction (replaces the per-tensor host loop between backward
    and step). Non-finite tensors are excluded from the norm so the
    gauge stays useful while the poisoned names are reported."""
    return grad_health_deferred(named_grads)()


def layer_breakdown(named_params: Iterable[Tuple[str, Any, Any]],
                    lr: Optional[float] = None,
                    depth: int = 1) -> Dict[str, dict]:
    """Per-layer-prefix grad/weight/update norms in ONE fused jitted
    reduction: `named_params` yields (qualified_name, weight, grad)
    triples; groups are the first `depth` dotted segments (the
    footprint() convention). The update norm is the SGD-style
    ``lr * grad_norm`` estimate (optimizer-family-exact update vectors
    would need a param snapshot per step); ``update_ratio`` =
    update_norm / weight_norm is the per-group learning-velocity signal
    (healthy training sits around 1e-3; ~0 means frozen, ~1e-1 means
    thrashing). Returns {group: {grad_norm, weight_norm, update_norm,
    update_ratio, n_tensors}}."""
    import numpy as np

    groups: List[str] = []
    arrays: List[Any] = []
    kinds: List[str] = []  # "w" or "g", interleaved in one device call
    for qual, w, g in named_params:
        group = ".".join(qual.split(".")[:depth]) or qual
        if w is not None:
            groups.append(group)
            arrays.append(_as_array(w))
            kinds.append("w")
        if g is not None:
            groups.append(group)
            arrays.append(_as_array(g))
            kinds.append("g")
    if not arrays:
        return {}
    sq, fin = _fused_norms(arrays)
    sq = _clamp_overflow(np.asarray(sq, dtype=np.float64))
    fin = np.asarray(fin, dtype=bool)
    out: Dict[str, dict] = {}
    acc: Dict[str, Dict[str, float]] = {}
    for group, kind, s, ok in zip(groups, kinds, sq, fin):
        a = acc.setdefault(group, {"w": 0.0, "g": 0.0, "n": 0})
        a["n"] += 1
        if ok:
            a[kind] += float(s)
    for group, a in acc.items():
        wn = math.sqrt(a["w"])
        gn = math.sqrt(a["g"])
        row = {"grad_norm": round(gn, 8), "weight_norm": round(wn, 8),
               "n_tensors": a["n"]}
        if lr is not None:
            un = abs(float(lr)) * gn
            row["update_norm"] = round(un, 10)
            row["update_ratio"] = round(un / wn, 10) if wn > 0 else None
        out[group] = row
    return out


# ---------------------------------------------------------------------------
# journal persistence (the goodput/memwatch contract, line-oriented:
# header line + one JSON line per closed step)
# ---------------------------------------------------------------------------


def journal_path(dir: Optional[str] = None) -> str:
    base = dir or _JOURNAL_DIR or "."
    return os.path.join(base,
                        f"dynamics.rank{_monitor.trainer_rank()}.jsonl")


def configure(dir: Optional[str] = None,
              flush_steps: Optional[int] = None,
              resume: bool = True) -> None:
    """Set up journal persistence; with `resume`, an existing journal
    seeds the step count, anomaly totals and the trajectory prefix — but
    only while the in-process ledger is still pristine (the goodput
    double-count guard)."""
    global _JOURNAL_DIR, _FLUSH_STEPS, _atexit_registered
    if dir:
        _JOURNAL_DIR = dir
        pristine = (_LEDGER.base is None and _LEDGER.steps == 0
                    and not _LEDGER.open)
        if resume and pristine:
            path = journal_path(dir)
            if os.path.exists(path):
                try:
                    _LEDGER.base = load_journal(path)
                except (OSError, ValueError):
                    _LEDGER.base = None  # torn/alien file: start fresh
        if not _atexit_registered:
            _atexit_registered = True
            atexit.register(_flush_at_exit)
    if flush_steps is not None:
        _FLUSH_STEPS = max(1, int(flush_steps))


def disable_persistence() -> None:
    """Supervisor hook (distributed/launch.py): its own exit must never
    clobber a real rank's journal."""
    global _JOURNAL_DIR
    _JOURNAL_DIR = None


def _rank_changed() -> None:
    """monitor.set_trainer_rank() notification — mirror of
    goodput._rank_changed: drop the old identity's base, re-resume
    against the new rank's journal while still pristine."""
    if _JOURNAL_DIR is None:
        return
    _LEDGER.base = None
    if _LEDGER.steps == 0 and not _LEDGER.open:
        path = journal_path()
        if os.path.exists(path):
            try:
                _LEDGER.base = load_journal(path)
            except (OSError, ValueError):
                _LEDGER.base = None


def _flush_at_exit() -> None:
    try:
        flush()
    except OSError:
        pass


def flush(path: Optional[str] = None) -> Optional[str]:
    """Write the journal (atomic temp + os.replace, like every other
    ledger): line 1 is the header doc, each following line one closed
    step — greppable, tail-able, and append-shaped without sacrificing
    the atomicity whole-file replacement buys. No-op when persistence is
    unconfigured and no path given."""
    if path is None:
        if _JOURNAL_DIR is None:
            return None
        path = journal_path()
    doc = totals()
    series = doc.pop("series", [])
    lines = [json.dumps(doc)]
    lines.extend(json.dumps(s) for s in series)
    return _monitor.atomic_write_text(path, "\n".join(lines) + "\n")


def load_journal(path: str) -> Dict[str, Any]:
    """Read a dynamics journal back into one doc: the header fields plus
    the step records under "series"."""
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"{path}: empty dynamics journal")
    header = json.loads(lines[0])
    if header.get("schema") != SCHEMA:
        raise ValueError(f"{path}: not a dynamics journal (schema "
                         f"{header.get('schema')!r})")
    header["series"] = [json.loads(ln) for ln in lines[1:]]
    return header


_JOURNAL_FILE_RE = re.compile(r"dynamics\.rank(\d+)\.jsonl$")


def load_journals(dir: str,
                  ranks: Optional[Sequence[int]] = None
                  ) -> Optional[Dict[str, Any]]:
    """Merge per-rank dynamics journals in `dir` (launch teardown,
    obs_report --dynamics). `ranks` limits to this job's membership."""
    want = set(int(r) for r in ranks) if ranks is not None else None
    docs = []
    for path in sorted(glob.glob(os.path.join(dir, "dynamics.rank*.jsonl"))):
        try:
            doc = load_journal(path)
        except (OSError, ValueError):
            continue
        if want is None or int(doc.get("rank", -1)) in want:
            docs.append(doc)
    return merge_ledgers(docs) if docs else None


# the desync probe's final-comparison window (closed steps per rank) and
# the default relative spread tolerance: under data parallelism every
# rank sees the same allreduced gradients, so curves should agree to
# well under 5% — a larger spread means the ranks are optimizing
# different objectives (broken grad sync, skewed sharding, a bad host)
DESYNC_WINDOW = 5
DESYNC_TOLERANCE = 0.05


def _final_window_loss(doc: Dict[str, Any],
                       window: int = DESYNC_WINDOW) -> Optional[float]:
    losses = [s["loss"] for s in doc.get("series", [])
              if s.get("loss") is not None
              and math.isfinite(float(s["loss"]))]
    if not losses:
        return None
    tail = losses[-window:]
    return sum(tail) / len(tail)


def check_desync(docs: List[Dict[str, Any]],
                 tolerance: float = DESYNC_TOLERANCE,
                 window: int = DESYNC_WINDOW) -> Dict[str, Any]:
    """Cross-rank loss-spread probe: compare each rank's final-window
    mean loss against the cross-rank median. Ranks deviating more than
    `tolerance` (relative) are desync suspects. Needs >= 2 ranks with
    recorded losses; `checked` is False otherwise."""
    finals: Dict[str, float] = {}
    for d in docs:
        val = _final_window_loss(d, window)
        if val is not None:
            finals[str(d.get("rank", len(finals)))] = val
    if len(finals) < 2:
        return {"checked": False, "n_ranks": len(finals),
                "tolerance": tolerance}
    ordered = sorted(finals.values())
    mid = len(ordered) // 2
    median = (ordered[mid] if len(ordered) % 2
              else 0.5 * (ordered[mid - 1] + ordered[mid]))
    scale = max(abs(median), 1e-12)
    deviation = {r: abs(v - median) / scale for r, v in finals.items()}
    suspects = sorted((r for r, dev in deviation.items()
                       if dev > tolerance), key=int)
    return {
        "checked": True,
        "n_ranks": len(finals),
        "window": window,
        "tolerance": tolerance,
        "median_loss": median,
        "spread": (max(ordered) - min(ordered)) / scale,
        "per_rank_loss": {r: finals[r] for r in sorted(finals, key=int)},
        "suspects": suspects,
        "ok": not suspects,
    }


def merge_ledgers(docs: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Cross-rank view: per-rank final losses and anomaly counts listed
    individually, anomaly totals summed, plus the desync probe verdict
    (the cheap DP-correctness check launch.py prints at teardown)."""
    per_rank: Dict[str, dict] = {}
    counts: Dict[str, int] = {k: 0 for k in ANOMALY_KINDS}
    steps = 0
    for d in docs:
        r = str(d.get("rank", len(per_rank)))
        rc = d.get("anomaly_counts") or {}
        per_rank[r] = {
            "steps": int(d.get("steps", 0)),
            "final_loss": _final_window_loss(d, 1),
            "final_window_loss": _final_window_loss(d),
            "loss_ema": d.get("loss_ema"),
            "anomalies_total": sum(int(v) for v in rc.values()),
        }
        for k in ANOMALY_KINDS:
            counts[k] += int(rc.get(k, 0))
        steps = max(steps, per_rank[r]["steps"])
    return {
        "schema": SCHEMA,
        "ranks": sorted(per_rank, key=int),
        "steps": steps,
        "anomaly_counts": counts,
        "anomalies_total": sum(counts.values()),
        "per_rank": dict(sorted(per_rank.items(), key=lambda kv: int(kv[0]))),
        "desync": check_desync(docs),
    }


def render_summary(doc: Dict[str, Any], title: str = "dynamics") -> str:
    """Human-readable one-glance table (launch teardown, obs_report)."""
    lines = [f"== {title}: {doc.get('steps', 0)} step(s), "
             f"{doc.get('anomalies_total', 0)} anomaly episode(s) =="]
    if doc.get("per_rank"):
        for r, row in doc["per_rank"].items():
            fl = row.get("final_window_loss")
            lines.append(
                f"  rank{r}: final_loss="
                f"{'-' if fl is None else f'{fl:.5f}'} "
                f"steps={row['steps']} anomalies={row['anomalies_total']}")
    elif doc.get("loss_ema") is not None:
        lines.append(f"  loss_ema={doc['loss_ema']:.5f} "
                     f"grad_norm_ema={doc.get('grad_norm_ema') or 0:.4g}")
    counts = {k: v for k, v in (doc.get("anomaly_counts") or {}).items()
              if v}
    if counts:
        lines.append("  episodes: " + ", ".join(
            f"{k}={v}" for k, v in sorted(counts.items())))
    desync = doc.get("desync")
    if desync and desync.get("checked"):
        if desync["suspects"]:
            lines.append(
                f"  DESYNC: rank(s) {','.join(desync['suspects'])} "
                f"deviate >{desync['tolerance'] * 100:.0f}% from the "
                f"cross-rank median loss (spread "
                f"{desync['spread'] * 100:.1f}%) — check gradient "
                f"synchronization")
        else:
            lines.append(
                f"  desync probe: OK ({desync['n_ranks']} rank(s), "
                f"loss spread {desync['spread'] * 100:.2f}%)")
    return "\n".join(lines)


# env-driven wiring: under launch.py (or a user export) every rank
# persists its dynamics journal with no code change
_env_dir = _flags.env_flag("PADDLE_TPU_DYNAMICS_DIR")
if _env_dir:
    try:
        os.makedirs(_env_dir, exist_ok=True)
        configure(dir=_env_dir)
    except OSError:
        pass  # unwritable dir: telemetry stays in-process only
