"""Per-rank live status endpoint: /metrics, /healthz, /status over HTTP.

Stdlib-only (http.server on a daemon thread): every rank of a job can be
scraped or eyeballed while it trains, with zero extra dependencies. The
three endpoints cover the three consumers:

  /metrics   Prometheus text exposition (monitor.to_prometheus()) — the
             scrape target; includes the goodput_* series
  /healthz   tiny liveness JSON (rank, pid, step-progress count)
  /status    the operator view (goodput.status()): current step,
             throughput EMA, goodput %, bucket breakdown, the
             flight-recorder tail of recent spans, a `memory` section
             (memwatch.status(): live bytes_in_use, lifetime peak,
             per-step watermark tail, leak-detector state), a
             `dynamics` section (dynamics.status(): loss/grad EMA
             state, anomaly counters, the recent trajectory tail), and
             a `serving` section (serving.ledger.status(): SLO table —
             tokens/s, TTFT/latency p50/p99 — batch occupancy, KV
             utilization, serving goodput buckets, span
             reconciliation; {available: false} until an engine runs)

Enable with PADDLE_TPU_STATUS_PORT=<port> (declared in flags.py; 0 =
off). distributed/launch.py assigns base-port+rank to each spawned rank
and prints the per-rank links. Serving must never interfere with
training: handlers catch their own failures and a busy port degrades to
a warning, not a crash.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from . import dynamics as _dynamics
from . import flags as _flags
from . import goodput as _goodput
from . import memwatch as _memwatch
from . import monitor as _monitor
from .serving import ledger as _serving_ledger

__all__ = ["start_status_server", "stop_status_server", "server_port"]

_ENDPOINTS = ("/status", "/metrics", "/healthz")

_SERVER: Optional[ThreadingHTTPServer] = None
_THREAD: Optional[threading.Thread] = None


class _StatusHandler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-status/1"

    def log_message(self, fmt, *args):  # no per-request stderr spam
        pass

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code: int, doc) -> None:
        self._send(code, json.dumps(doc, indent=1), "application/json")

    def do_GET(self):  # noqa: N802 (http.server contract)
        path = self.path.split("?", 1)[0].rstrip("/") or "/status"
        try:
            if path == "/metrics":
                self._send(200, _monitor.to_prometheus(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                self._send_json(200, {
                    "status": "ok",
                    "rank": _monitor.trainer_rank(),
                    "pid": os.getpid(),
                    "progress": _monitor.progress_count(),
                    "time_unix": time.time(),
                })
            elif path == "/status":
                doc = _goodput.status()
                doc["memory"] = _memwatch.status()
                doc["dynamics"] = _dynamics.status()
                doc["serving"] = _serving_ledger.status()
                self._send_json(200, doc)
            else:
                self._send_json(404, {"error": f"unknown path {path!r}",
                                      "endpoints": list(_ENDPOINTS)})
        except Exception as e:  # serving must never take down training
            try:
                self._send_json(500, {"error": repr(e)})
            except OSError:
                pass


def start_status_server(port: Optional[int] = None,
                        host: Optional[str] = None) -> ThreadingHTTPServer:
    """Start (or return the already-running) status server. `port` 0
    binds an ephemeral port — read it back via `server_port()`.
    Loopback-only by default: the endpoints are unauthenticated, so
    exposing them beyond the host (a Prometheus scraper on another
    node) is an explicit opt-in — `host="0.0.0.0"` here, or
    PADDLE_TPU_STATUS_HOST=0.0.0.0 for the env-wired path."""
    global _SERVER, _THREAD
    if _SERVER is not None:
        return _SERVER
    if port is None:
        port = int(_flags.env_flag("PADDLE_TPU_STATUS_PORT"))
    if host is None:
        host = str(_flags.env_flag("PADDLE_TPU_STATUS_HOST"))
    srv = ThreadingHTTPServer((host, int(port)), _StatusHandler)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever,
                         name="paddle-tpu-status", daemon=True)
    t.start()
    _SERVER, _THREAD = srv, t
    return srv


def stop_status_server() -> None:
    global _SERVER, _THREAD
    if _SERVER is not None:
        _SERVER.shutdown()
        _SERVER.server_close()
    _SERVER = _THREAD = None


def server_port() -> Optional[int]:
    return _SERVER.server_port if _SERVER is not None else None


# env-driven wiring: launch.py exports PADDLE_TPU_STATUS_PORT=base+rank
# per spawned rank; standalone runs export it by hand. A taken port must
# degrade to a warning — the job matters more than its dashboard.
_env_port = int(_flags.env_flag("PADDLE_TPU_STATUS_PORT"))
if _env_port > 0:
    try:
        start_status_server(_env_port)
    except OSError as e:
        print(f"[paddle_tpu.status] could not bind status port "
              f"{_env_port}: {e}", file=sys.stderr)
