"""Per-rank live status endpoint: /metrics, /healthz, /status over HTTP.

Stdlib-only (http.server on a daemon thread): every rank of a job can be
scraped or eyeballed while it trains, with zero extra dependencies. The
three endpoints cover the three consumers:

  /metrics   Prometheus text exposition (monitor.to_prometheus()) — the
             scrape target; includes the goodput_* series
  /healthz   tiny liveness JSON (rank, pid, step-progress count); when
             this process registered a serving engine
             (serving.set_replica_engine) it also carries the engine's
             `serving` sub-document (draining/active/queued) — the
             router's health + least-loaded input
  /generate  POST (serving replicas only): dispatch one generation
             request into the registered engine — {request_id, prompt,
             max_new_tokens, deadline_s} -> {tokens, cached, rank}.
             503 when no engine is registered or the replica is
             draining; failures return the TYPED error name, never a
             hang (serving/router.py is the intended client)
  /drain     POST: begin connection draining — the engine finishes
             admitted work, rejects new submissions, and /healthz
             reports drained once idle
  /status    the operator view (goodput.status()): current step,
             throughput EMA, goodput %, bucket breakdown, the
             flight-recorder tail of recent spans, a `memory` section
             (memwatch.status(): live bytes_in_use, lifetime peak,
             per-step watermark tail, leak-detector state), a
             `dynamics` section (dynamics.status(): loss/grad EMA
             state, anomaly counters, the recent trajectory tail), a
             `comms` section (commswatch.status(): measured per-(kind,
             axis, size-bucket) bus bandwidth, per-axis attribution of
             the collective wall, barrier-skew straggler state, the
             predicted-vs-measured reconciliation), and
             a `serving` section (serving.ledger.status(): SLO table —
             tokens/s, TTFT/latency p50/p99 — batch occupancy, KV
             utilization, serving goodput buckets, span
             reconciliation; {available: false} until an engine runs)

Enable with PADDLE_TPU_STATUS_PORT=<port> (declared in flags.py; 0 =
off). distributed/launch.py assigns base-port+rank to each spawned rank
and prints the per-rank links. Serving must never interfere with
training: handlers catch their own failures and a busy port degrades to
a warning, not a crash.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from . import commswatch as _commswatch
from . import dynamics as _dynamics
from . import flags as _flags
from . import goodput as _goodput
from . import memwatch as _memwatch
from . import monitor as _monitor
from .serving import ledger as _serving_ledger

__all__ = ["start_status_server", "stop_status_server", "server_port"]

_ENDPOINTS = ("/status", "/metrics", "/healthz", "/generate", "/drain")

_SERVER: Optional[ThreadingHTTPServer] = None
_THREAD: Optional[threading.Thread] = None


class _StatusHandler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-status/1"

    def log_message(self, fmt, *args):  # no per-request stderr spam
        pass

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code: int, doc) -> None:
        self._send(code, json.dumps(doc, indent=1), "application/json")

    def do_GET(self):  # noqa: N802 (http.server contract)
        path = self.path.split("?", 1)[0].rstrip("/") or "/status"
        try:
            if path == "/metrics":
                self._send(200, _monitor.to_prometheus(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                doc = {
                    "status": "ok",
                    "rank": _monitor.trainer_rank(),
                    "pid": os.getpid(),
                    "progress": _monitor.progress_count(),
                    "time_unix": time.time(),
                }
                engine = _replica_engine()
                if engine is not None:
                    doc["serving"] = engine.healthz_info()
                self._send_json(200, doc)
            elif path == "/status":
                doc = _goodput.status()
                doc["memory"] = _memwatch.status()
                doc["dynamics"] = _dynamics.status()
                doc["comms"] = _commswatch.status()
                doc["serving"] = _serving_ledger.status()
                self._send_json(200, doc)
            else:
                self._send_json(404, {"error": f"unknown path {path!r}",
                                      "endpoints": list(_ENDPOINTS)})
        except Exception as e:  # serving must never take down training
            try:
                self._send_json(500, {"error": repr(e)})
            except OSError:
                pass

    def do_POST(self):  # noqa: N802 (http.server contract)
        path = self.path.split("?", 1)[0].rstrip("/")
        try:
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length) if length else b""
            body = json.loads(raw.decode() or "{}") if raw else {}
        except (ValueError, OSError) as e:
            self._send_json(400, {"error": f"bad request body: {e!r}"})
            return
        try:
            if path == "/generate":
                self._handle_generate(body)
            elif path == "/drain":
                self._handle_drain()
            else:
                self._send_json(404, {"error": f"unknown path {path!r}",
                                      "endpoints": list(_ENDPOINTS)})
        except Exception as e:
            try:
                self._send_json(500, {"error": repr(e)})
            except OSError:
                pass

    def _handle_generate(self, body: dict) -> None:
        """The replica-side dispatch endpoint: one generation request
        into the registered engine. Failures are TYPED json (the error
        class name the router surfaces), bounded (the wait cannot outlive
        the request's deadline by more than a grace beat) — a dead or
        draining replica answers loudly, it never hangs the caller."""
        from .framework import errors as _errors

        engine = _replica_engine()
        if engine is None:
            self._send_json(503, {"error": "no serving engine registered "
                                  "on this rank"})
            return
        prompt = body.get("prompt")
        if not isinstance(prompt, list) or not prompt:
            self._send_json(400, {"error": "prompt must be a non-empty "
                                  "token list"})
            return
        rid = body.get("request_id") or None
        deadline_s = float(body.get("deadline_s")
                           or engine.default_slo_s)
        try:
            handle = engine.submit(
                prompt, max_new_tokens=int(body.get("max_new_tokens", 16)),
                deadline_s=deadline_s, request_id=rid,
                trace=body.get("__trace__") or None)
            # +1s past the deadline, strictly INSIDE the router client's
            # socket timeout (+2s): the typed 504 must reach the caller
            # before its transport gives up, and an abandoned request
            # must not pin this handler thread
            tokens = handle.result(timeout=deadline_s + 1.0)
        except _errors.errors.Unavailable as e:
            self._send_json(503, {
                "error": str(e)[:500], "error_type": type(e).__name__,
                "draining": engine.draining})
            return
        except _errors.errors.ExecutionTimeout as e:
            self._send_json(504, {"error": str(e)[:500],
                                  "error_type": type(e).__name__})
            return
        except Exception as e:
            self._send_json(500, {"error": str(e)[:500],
                                  "error_type": type(e).__name__})
            return
        self._send_json(200, {
            "request_id": handle.request_id,
            "tokens": [int(t) for t in tokens],
            "cached": bool(handle.cached),
            "rank": _monitor.trainer_rank(),
            "pid": os.getpid(),
            # the engine-side latency decomposition rides the reply so
            # the router can assemble the FULL-STACK attribution record
            # (its buckets + transport + these) without a second RPC
            "attribution": handle.attribution,
            "engine_e2e_s": handle.engine_e2e_s,
        })

    def _handle_drain(self) -> None:
        engine = _replica_engine()
        if engine is None:
            self._send_json(503, {"error": "no serving engine registered "
                                  "on this rank"})
            return
        engine.drain()
        self._send_json(200, {"draining": True,
                              "drained": engine.drained(),
                              **engine.healthz_info()})


def _replica_engine():
    from . import serving as _serving

    return _serving.replica_engine()


def start_status_server(port: Optional[int] = None,
                        host: Optional[str] = None) -> ThreadingHTTPServer:
    """Start (or return the already-running) status server. `port` 0
    binds an ephemeral port — read it back via `server_port()`.
    Loopback-only by default: the endpoints are unauthenticated, so
    exposing them beyond the host (a Prometheus scraper on another
    node) is an explicit opt-in — `host="0.0.0.0"` here, or
    PADDLE_TPU_STATUS_HOST=0.0.0.0 for the env-wired path."""
    global _SERVER, _THREAD
    if _SERVER is not None:
        return _SERVER
    if port is None:
        port = int(_flags.env_flag("PADDLE_TPU_STATUS_PORT"))
    if host is None:
        host = str(_flags.env_flag("PADDLE_TPU_STATUS_HOST"))
    srv = ThreadingHTTPServer((host, int(port)), _StatusHandler)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever,
                         name="paddle-tpu-status", daemon=True)
    t.start()
    _SERVER, _THREAD = srv, t
    return srv


def stop_status_server() -> None:
    global _SERVER, _THREAD
    if _SERVER is not None:
        _SERVER.shutdown()
        _SERVER.server_close()
    _SERVER = _THREAD = None


def server_port() -> Optional[int]:
    return _SERVER.server_port if _SERVER is not None else None


def free_port() -> int:
    """An ephemeral loopback port (bind-0 probe) — THE shared helper
    the multi-process benches (serve_bench, chaos_bench,
    dp_comms_bench) use to place coordination/status endpoints."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# env-driven wiring: launch.py exports PADDLE_TPU_STATUS_PORT=base+rank
# per spawned rank; standalone runs export it by hand. A taken port must
# degrade to a warning — the job matters more than its dashboard.
_env_port = int(_flags.env_flag("PADDLE_TPU_STATUS_PORT"))
if _env_port > 0:
    try:
        start_status_server(_env_port)
    except OSError as e:
        print(f"[paddle_tpu.status] could not bind status port "
              f"{_env_port}: {e}", file=sys.stderr)
