"""Atomic full-state training checkpoints + auto-resume (the fault plane's
recovery half).

A respawned rank that restarts from step 0 turns every failure into a
full-run badput event; the MLPerf TPU-pod playbook ranks exactly that
restart badput among the top obstacles to pod-scale goodput. This module
persists the COMPLETE training state the fit loop needs to continue as
if the crash never happened:

- parameters (``network.state_dict()``),
- optimizer state — every accumulator slot (Adam moments, beta powers,
  velocity...), the LR-scheduler state, AND the ``__dp_comms__``
  error-feedback residuals (a quantized-allreduce restart that lost its
  compensation buffers would re-inject the dropped quantization error),
- the global step counter,
- the data/RNG cursor (epoch + step-in-epoch + the numpy global RNG
  state, so shuffles and data order continue deterministically).

Writes are atomic in the ``monitor.atomic_write_text`` idiom (same-dir
temp + ``os.replace``; a crash mid-write leaves the previous checkpoint
intact, never a torn file) and carry a content digest so a resume can
assert bit-identity. A retention window (``PADDLE_TPU_CKPT_KEEP``)
sweeps older checkpoints as new ones land.

Restore pre-seeds the optimizer's accumulator store directly (dygraph
optimizers create accumulators lazily at the first step — a plain
``set_state_dict`` before any step would silently restore nothing), so
the FIRST resumed update already runs on the restored moments:
bit-identical continuation, asserted by the chaos tests.

Env knobs (flags.py registry): PADDLE_TPU_CKPT_DIR enables the fit
loop's auto-checkpoint/auto-resume, PADDLE_TPU_CKPT_STEPS the cadence,
PADDLE_TPU_CKPT_KEEP the retention window.
"""
from __future__ import annotations

import glob
import hashlib
import os
import pickle
import re
import time
from typing import Any, Dict, Optional

import numpy as np

from . import flags as _flags
from . import monitor as _monitor

__all__ = [
    "SCHEMA", "TrainCheckpointer", "from_env", "state_digest",
    "atomic_write_bytes", "latest_path", "load",
]

SCHEMA = "paddle_tpu.trainckpt/1"

_FILE_RE = re.compile(r"trainckpt\.rank(\d+)\.step(\d+)\.pdz$")

_M_SAVED = _monitor.counter(
    "train_checkpoint_saved_total", "training checkpoints written")
_M_RESUMED = _monitor.counter(
    "train_checkpoint_resumed_total", "training resumes from a checkpoint")


def atomic_write_bytes(path: str, data: bytes) -> str:
    """Binary checkpoint writes ride THE one atomicity implementation
    (monitor.atomic_write: same-dir temp + os.replace + the io_stall
    chaos site — a checkpoint flush is exactly the write a wedged disk
    stalls)."""
    return _monitor.atomic_write(path, data)


def _to_numpy(v) -> np.ndarray:
    inner = getattr(v, "_value", None)
    return np.asarray(inner if inner is not None else v)


def _digest_update(h, obj, prefix: str = "") -> None:
    if isinstance(obj, dict):
        for k in sorted(obj):
            _digest_update(h, obj[k], f"{prefix}/{k}")
        return
    if isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            _digest_update(h, v, f"{prefix}[{i}]")
        return
    if isinstance(obj, np.ndarray) or hasattr(obj, "shape"):
        a = np.ascontiguousarray(np.asarray(obj))
        h.update(f"{prefix}:{a.dtype}:{a.shape}:".encode())
        h.update(a.tobytes())
        return
    h.update(f"{prefix}={obj!r};".encode())


def state_digest(*states: Any) -> str:
    """Content digest over nested state containers (arrays hashed by
    dtype+shape+bytes, scalars by repr) — equal iff the states are
    bit-identical. The chaos test's resume-equality oracle."""
    h = hashlib.sha1()
    for s in states:
        _digest_update(h, s)
    return h.hexdigest()


def _content_digest(params: Dict[str, Any], accumulators: Dict[str, Any],
                    opt_state: Dict[str, Any]) -> str:
    """The checkpoint digest: params + accumulator VALUES (keyed by the
    process-independent structured name — the raw framework names a
    respawn re-generates must not perturb equality) + the __dp_comms__
    error-feedback residuals."""
    acc_values = {
        slot: {key: rec.get("value") for key, rec in per.items()}
        for slot, per in (accumulators or {}).items()
    }
    return state_digest(params, acc_values,
                        (opt_state or {}).get("__dp_comms__", {}))


def latest_path(dir: str, rank: Optional[int] = None) -> Optional[str]:
    """Newest (highest-step) checkpoint of `rank` in `dir`, or None."""
    rank = _monitor.trainer_rank() if rank is None else int(rank)
    best: Optional[tuple] = None
    for p in glob.glob(os.path.join(dir, "trainckpt.rank*.step*.pdz")):
        m = _FILE_RE.search(os.path.basename(p))
        if not m or int(m.group(1)) != rank:
            continue
        step = int(m.group(2))
        if best is None or step > best[0]:
            best = (step, p)
    return best[1] if best else None


def load(path: str) -> Dict[str, Any]:
    with open(path, "rb") as f:
        doc = pickle.load(f)
    if not isinstance(doc, dict) or doc.get("schema") != SCHEMA:
        raise ValueError(f"{path}: not a training checkpoint (schema "
                         f"{doc.get('schema') if isinstance(doc, dict) else None!r})")
    return doc


class TrainCheckpointer:
    """Periodic atomic checkpoints for one rank's fit loop."""

    def __init__(self, dir: str, every_steps: Optional[int] = None,
                 keep: Optional[int] = None, rank: Optional[int] = None):
        self.dir = dir
        self.every_steps = max(1, int(
            every_steps if every_steps is not None
            else _flags.env_flag("PADDLE_TPU_CKPT_STEPS")))
        self.keep = max(1, int(
            keep if keep is not None
            else _flags.env_flag("PADDLE_TPU_CKPT_KEEP")))
        self.rank = _monitor.trainer_rank() if rank is None else int(rank)
        self.last_saved_step: Optional[int] = None

    def path_for(self, step: int) -> str:
        return os.path.join(
            self.dir, f"trainckpt.rank{self.rank}.step{int(step):08d}.pdz")

    # -- save -----------------------------------------------------------

    def save(self, network, optimizer, step: int,
             data_cursor: Optional[Dict[str, Any]] = None,
             rng_state=None) -> str:
        """Write one checkpoint: everything the resumed rank needs to
        continue bit-identically from `step` completed steps.
        ``rng_state`` is the numpy RNG state to restore BEFORE resuming
        the data iteration (the fit loop passes the epoch-start state,
        from before the loader drew its shuffle permutation); default:
        the current state."""
        params = {name: _to_numpy(p)
                  for name, p in network.state_dict().items()}
        opt_state, accumulators = self._optimizer_state(
            optimizer, network=network)
        doc = {
            "schema": SCHEMA,
            "rank": self.rank,
            "pid": os.getpid(),
            "time_unix": time.time(),
            "step": int(step),
            "params": params,
            "optimizer": opt_state,
            "accumulators": accumulators,
            "data_cursor": dict(data_cursor or {}),
            "numpy_rng": (rng_state if rng_state is not None
                          else np.random.get_state()),
        }
        doc["digest"] = _content_digest(params, accumulators, opt_state)
        path = self.path_for(step)
        atomic_write_bytes(path, pickle.dumps(doc, protocol=4))
        self.last_saved_step = int(step)
        _M_SAVED.inc()
        _monitor.flight_record("checkpoint", "saved", step=int(step),
                               path=os.path.basename(path))
        self._sweep()
        return path

    def maybe_save(self, network, optimizer, step: int,
                   data_cursor: Optional[Dict[str, Any]] = None,
                   rng_state=None) -> Optional[str]:
        """Cadence gate: save when `step` completed steps hit the
        every_steps boundary (and only once per boundary)."""
        if step <= 0 or step % self.every_steps != 0:
            return None
        if self.last_saved_step == step:
            return None
        return self.save(network, optimizer, step, data_cursor,
                         rng_state=rng_state)

    @staticmethod
    def _optimizer_state(optimizer, network=None) -> tuple:
        """(flat state_dict, structured {slot: {param_key: {name,
        value}}}). The structured half is what lets restore pre-seed the
        lazily-created accumulator store on a fresh process. Keys prefer
        the network's STRUCTURED parameter names (``0.weight``), which
        survive the process-global unique-name counter a respawn (or a
        rebuilt model) re-winds; the raw framework name is kept alongside
        for translation back."""
        if optimizer is None:
            return {}, {}
        flat = {}
        for k, v in optimizer.state_dict().items():
            flat[k] = v if k in ("LR_Scheduler", "__dp_comms__") \
                else np.asarray(v)
        qual_of = {}
        if network is not None:
            qual_of = {getattr(p, "name", qual): qual
                       for qual, p in network.named_parameters()}
        structured: Dict[str, Dict[str, dict]] = {}
        for slot, per_param in getattr(optimizer, "_accumulators",
                                       {}).items():
            structured[slot] = {
                qual_of.get(pname, pname): {
                    "name": getattr(var, "name", None),
                    "param_name": pname,
                    "value": _to_numpy(var)}
                for pname, var in per_param.items()
            }
        return flat, structured

    def _sweep(self) -> None:
        """Retention: keep the newest `keep` checkpoints of this rank."""
        mine = []
        for p in glob.glob(os.path.join(
                self.dir, f"trainckpt.rank{self.rank}.step*.pdz")):
            m = _FILE_RE.search(os.path.basename(p))
            if m and int(m.group(1)) == self.rank:
                mine.append((int(m.group(2)), p))
        for _, p in sorted(mine)[:-self.keep]:
            try:
                os.unlink(p)
            except OSError:
                pass  # a raced unlink must not kill the training loop

    # -- restore --------------------------------------------------------

    def load_latest(self) -> Optional[Dict[str, Any]]:
        path = latest_path(self.dir, self.rank)
        if path is None:
            return None
        try:
            return load(path)
        except (OSError, ValueError, pickle.UnpicklingError):
            return None  # a torn file cannot happen (atomic); an alien can

    def restore(self, network, optimizer, doc: Dict[str, Any],
                restore_rng: bool = True) -> int:
        """Apply a checkpoint: params, optimizer accumulators (pre-seeded
        into the lazy store so the FIRST resumed step updates on the
        restored moments), LR scheduler + __dp_comms__ residuals, and
        the numpy RNG cursor. Returns the completed-step count."""
        network.set_state_dict(doc["params"])
        if optimizer is not None:
            self._restore_accumulators(optimizer, doc.get("accumulators"),
                                       network=network)
            optimizer.set_state_dict(doc.get("optimizer") or {})
        if restore_rng and doc.get("numpy_rng") is not None:
            np.random.set_state(doc["numpy_rng"])
        self.last_saved_step = int(doc["step"])
        _M_RESUMED.inc()
        _monitor.flight_record("checkpoint", "resumed",
                               step=int(doc["step"]),
                               digest=doc.get("digest"))
        return int(doc["step"])

    @staticmethod
    def _restore_accumulators(optimizer, structured, network=None) -> None:
        if not structured:
            return
        import jax.numpy as jnp

        from .dygraph.varbase import Tensor

        # translate structured parameter keys back to THIS process's
        # framework names (the respawn may have re-wound the unique-name
        # counter differently than the dead attempt)
        name_of = {}
        if network is not None:
            name_of = {qual: getattr(p, "name", qual)
                       for qual, p in network.named_parameters()}
        for slot, per_param in structured.items():
            store = optimizer._accumulators.setdefault(slot, {})
            for key, rec in per_param.items():
                pname = name_of.get(key, rec.get("param_name", key))
                existing = store.get(pname)
                if existing is not None and hasattr(existing, "_dy_value"):
                    existing._dy_value = jnp.asarray(rec["value"])
                    continue
                if existing is not None and hasattr(existing, "_value"):
                    existing._value = jnp.asarray(rec["value"])
                    continue
                store[pname] = Tensor(
                    jnp.asarray(rec["value"]),
                    name=rec.get("name") or f"{pname}_{slot}_resume",
                    stop_gradient=True, persistable=True)

    def current_digest(self, network, optimizer) -> str:
        """Digest of the LIVE state, shaped exactly like the saved one —
        the equality oracle the bit-identical-resume tests compare."""
        params = {name: _to_numpy(p)
                  for name, p in network.state_dict().items()}
        opt_state, accumulators = self._optimizer_state(
            optimizer, network=network)
        return _content_digest(params, accumulators, opt_state)


def from_env() -> Optional[TrainCheckpointer]:
    """The fit loop's wiring: a TrainCheckpointer when
    PADDLE_TPU_CKPT_DIR is set, else None."""
    dir = str(_flags.env_flag("PADDLE_TPU_CKPT_DIR")).strip()
    if not dir:
        return None
    try:
        os.makedirs(dir, exist_ok=True)
    except OSError:
        return None  # unwritable dir: checkpointing stays off
    return TrainCheckpointer(dir)
