// Go inference binding for paddle_tpu over the C ABI
// (csrc/capi.cc + csrc/paddle_tpu_capi.h).
//
// Counterpart of the reference Go binding
// (/root/reference/go/paddle/predictor.go — cgo over the fluid C API).
// The TPU build's C ABI is narrower (ZeroCopy-style float32 run), so
// Predictor carries the Config inline and Tensor wraps the returned
// buffer; see config.go / tensor.go for the split mirroring the
// reference file layout.
//
// Build: CGO_CFLAGS="-I${REPO}/csrc" CGO_LDFLAGS="-L${REPO}/csrc/build \
//        -lpaddle_tpu_capi" go build ./...
package paddle

// #cgo CFLAGS: -I${SRCDIR}/../../csrc
// #cgo LDFLAGS: -L${SRCDIR}/../../csrc/build -lpaddle_tpu_capi
// #include <stdlib.h>
// #include <stdint.h>
// #include "paddle_tpu_capi.h"
import "C"

import (
	"errors"
	"runtime"
	"unsafe"
)

type Predictor struct {
	c *C.PD_Predictor
}

// NewPredictor loads a saved inference model (the save_inference_model
// directory format) — reference NewPredictor(config).
func NewPredictor(config *AnalysisConfig) (*Predictor, error) {
	dir := C.CString(config.ModelDir)
	defer C.free(unsafe.Pointer(dir))
	cp := C.PD_NewPredictor(dir)
	if cp == nil {
		return nil, errors.New("paddle_tpu: failed to load model from " + config.ModelDir)
	}
	p := &Predictor{c: cp}
	runtime.SetFinalizer(p, (*Predictor).finalize)
	return p, nil
}

func (p *Predictor) finalize() {
	if p.c != nil {
		C.PD_DeletePredictor(p.c)
		p.c = nil
	}
}

// GetInputNum mirrors the reference Predictor.GetInputNum.
func (p *Predictor) GetInputNum() int {
	return int(C.PD_GetInputNum(p.c))
}

// Run executes the model on float32 inputs and returns output 0.
func (p *Predictor) Run(inputs []*Tensor) (*Tensor, error) {
	n := len(inputs)
	data := make([]*C.float, n)
	shapes := make([]*C.int64_t, n)
	ndims := make([]C.int, n)
	// keep the Go buffers alive across the cgo call
	pinned := make([][]float32, n)
	pinnedShapes := make([][]int64, n)
	for i, t := range inputs {
		pinned[i] = t.Data
		pinnedShapes[i] = t.Shape
		data[i] = (*C.float)(unsafe.Pointer(&pinned[i][0]))
		shapes[i] = (*C.int64_t)(unsafe.Pointer(&pinnedShapes[i][0]))
		ndims[i] = C.int(len(t.Shape))
	}
	var outData *C.float
	var outShape *C.int64_t
	var outNdim C.int
	rc := C.PD_PredictorRunFloat(
		p.c,
		(**C.float)(unsafe.Pointer(&data[0])),
		(**C.int64_t)(unsafe.Pointer(&shapes[0])),
		(*C.int)(unsafe.Pointer(&ndims[0])),
		C.int(n), &outData, &outShape, &outNdim,
	)
	runtime.KeepAlive(pinned)
	runtime.KeepAlive(pinnedShapes)
	// the finalizer must not free the C predictor mid-call
	runtime.KeepAlive(p)
	if rc != 0 {
		return nil, errors.New("paddle_tpu: predictor run failed")
	}
	defer C.free(unsafe.Pointer(outData))
	defer C.free(unsafe.Pointer(outShape))

	nd := int(outNdim)
	shape := make([]int64, nd)
	numel := int64(1)
	cshape := unsafe.Slice((*int64)(unsafe.Pointer(outShape)), nd)
	for i := 0; i < nd; i++ {
		shape[i] = cshape[i]
		numel *= shape[i]
	}
	out := make([]float32, numel)
	cdata := unsafe.Slice((*float32)(unsafe.Pointer(outData)), numel)
	copy(out, cdata)
	return &Tensor{Shape: shape, Data: out}, nil
}
