// Tensor — reference go/paddle/tensor.go (ZeroCopyTensor). The TPU C
// ABI copies float32 buffers across the boundary, so the Go tensor is a
// plain (shape, data) pair.
package paddle

type Tensor struct {
	Shape []int64
	Data  []float32
}

func NewTensor(shape []int64, data []float32) *Tensor {
	return &Tensor{Shape: shape, Data: data}
}

func (t *Tensor) Numel() int64 {
	n := int64(1)
	for _, d := range t.Shape {
		n *= d
	}
	return n
}
