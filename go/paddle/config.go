// AnalysisConfig — reference go/paddle/config.go. The TPU build's
// predictor jit-compiles through XLA, so the reference's GPU/MKLDNN/TRT
// switches have no equivalent; the config is the model location plus
// the switches that translate.
package paddle

type AnalysisConfig struct {
	ModelDir string
}

// SetModel mirrors reference AnalysisConfig.SetModel(dir).
func (c *AnalysisConfig) SetModel(dir string) {
	c.ModelDir = dir
}

func (c *AnalysisConfig) Model() string {
	return c.ModelDir
}
