module paddle_tpu/go/smoke

go 1.20

require paddle_tpu/go/paddle v0.0.0

replace paddle_tpu/go/paddle => ../paddle
