// Go binding smoke test: load a saved LeNet inference model and run one
// float32 batch (wired into the python test suite behind a go-present
// guard, tests/test_go_binding.py).
package main

import (
	"fmt"
	"os"

	paddle "paddle_tpu/go/paddle"
)

func main() {
	if len(os.Args) < 2 {
		fmt.Println("usage: smoke <model_dir>")
		os.Exit(2)
	}
	cfg := &paddle.AnalysisConfig{}
	cfg.SetModel(os.Args[1])
	pred, err := paddle.NewPredictor(cfg)
	if err != nil {
		fmt.Println("ERR", err)
		os.Exit(1)
	}
	n := pred.GetInputNum()
	in := paddle.NewTensor([]int64{1, 1, 28, 28}, make([]float32, 28*28))
	out, err := pred.Run([]*paddle.Tensor{in})
	if err != nil {
		fmt.Println("ERR", err)
		os.Exit(1)
	}
	fmt.Printf("OK inputs=%d out_shape=%v numel=%d\n", n, out.Shape, out.Numel())
}
