"""Headline benchmark: GPT pretraining step throughput + MFU on one chip.

The reference publishes no in-repo numbers (BASELINE.md); the north star is
ERNIE/BERT-class pretraining at >= A100-NCCL MFU. Two configs run, each a
full training step (forward, backward, Adam) as one XLA program:

- gpt2s @ seq 512 (the round-1/2 headline, XLA-fused attention path)
- gpt2s @ seq 2048 (long sequence: the pallas flash-attention kernel's
  regime — the bench asserts via ops.attention.FLASH_DISPATCH_COUNT that
  the flash path was actually dispatched at trace time, so the kernel's
  perf claim is driver-verified rather than advertised; a silent XLA
  fallback fails the run)

Prints ONE JSON line: the headline {"metric", "value", "unit",
"vs_baseline"} plus a "long_seq" sub-object with the seq-2048 numbers.
"""
import json
import os
import time

import numpy as np


def bench_config(batch, seq, iters, n_layer=12, n_head=12, d_model=768):
    import jax

    from paddle_tpu import goodput as _goodput
    from paddle_tpu import memwatch as _memwatch
    from paddle_tpu.framework import Executor, Scope, program_guard
    from paddle_tpu.framework import shard_insight as _shard
    from paddle_tpu.models.gpt import GPTConfig, build_train_program
    from paddle_tpu.optimizer import Adam

    # per-config HBM window: everything from build through the timed
    # loops contributes to this config's measured peak watermark
    _memwatch.reset_window()
    # per-config comms window: the measured collective byte counters at
    # config start, so predicted-vs-measured reconciles over exactly the
    # steps this config ran
    coll_before = _shard.measured_collective_bytes()

    cfg = GPTConfig(
        vocab_size=32768,
        n_layer=n_layer,
        n_head=n_head,
        d_model=d_model,
        max_seq_len=seq,
        dtype="bfloat16",
    )
    main_prog, startup, io = build_train_program(cfg, batch=batch, seq=seq)
    with program_guard(main_prog, startup):
        Adam(learning_rate=1e-4).minimize(io["loss"])

    scope = Scope()
    exe = Executor()
    exe.run(startup, scope=scope)

    n_params = sum(int(np.prod(p.shape)) for p in main_prog.all_parameters())

    r = np.random.RandomState(0)
    # device-resident feeds: the measured loop is the training step, not
    # the h2d transfer (the DataLoader path overlaps transfers with compute)
    tokens = jax.device_put(r.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64))
    labels = jax.device_put(r.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64))
    feed = {"tokens": tokens, "labels": labels}

    # compile + warmup
    for _ in range(3):
        loss = exe.run(main_prog, feed=feed, fetch_list=[io["loss"]], scope=scope)[0]
    assert np.isfinite(float(loss)), loss

    # three timed windows: the remote device tunnel shows 10-20% run-to-run
    # interference. The headline uses the MEDIAN window (steady-state rate,
    # comparable to the A100 baseline's methodology); best and all windows
    # are reported alongside so the interference claim is auditable.
    dts = []
    gp_before = _goodput.totals()["buckets"]
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = exe.run(main_prog, feed=feed, fetch_list=[io["loss"]], scope=scope, return_numpy=False)
        # force the final value to the host: on remote-tunnel devices
        # block_until_ready can return before execution drains
        assert np.isfinite(float(np.asarray(out[0])))
        dts.append(time.perf_counter() - t0)
    med_dt = sorted(dts)[len(dts) // 2]

    # step-time attribution over the measured windows (goodput ledger
    # delta): device-compute seconds vs. everything else, so each
    # BENCH_r*.json round carries where its seconds went, not just totals
    gp_after = _goodput.totals()["buckets"]
    wall = sum(dts)
    gp_buckets = {b: round(gp_after[b] - gp_before.get(b, 0.0), 6)
                  for b in gp_after}
    gp_buckets["host_other"] = round(
        gp_buckets["host_other"]
        + max(0.0, wall - sum(gp_buckets.values())), 6)
    productive = sum(gp_buckets[b] for b in _goodput.PRODUCTIVE_BUCKETS)
    goodput_breakdown = {
        "wall_seconds": round(wall, 6),
        "steps": 3 * iters,
        "buckets": gp_buckets,
        "goodput_fraction": round(productive / wall, 4) if wall > 0 else None,
        # the lower-is-better comms headline perf_gate tracks: host
        # seconds blocked on collectives over the measured wall (~0 on
        # one chip — the DP comms layer is inert at nranks==1, and this
        # row is the gate that keeps it that way)
        "collective_fraction": (round(gp_buckets["collective"] / wall, 6)
                                if wall > 0 else None),
    }

    tok_s = batch * seq * iters / med_dt
    window_tok_s = [batch * seq * iters / d for d in dts]
    # standard 6ND transformer train FLOPs + attention term 12*L*T*D per token
    flops_per_token = 6 * n_params + 12 * n_layer * seq * d_model
    achieved = tok_s * flops_per_token

    # compiler-side accounting (xla_insight capture on the compile path):
    # the train step is the most expensive program in the executor cache.
    # Unlike the 6ND analytic model above, these are the FLOPs XLA says
    # the compiled program executes — utilization from them is auditable
    # against the dumped HLO (tools/xla_report.py)
    xla_cost = None
    insights = exe.compiled_insights()
    if insights:
        flops_per_step = max((c.get("flops") or 0) for c in insights)
        if flops_per_step > 0:
            steps_per_sec = iters / med_dt
            xla_cost = {
                "flops_per_step": round(flops_per_step),
                "steps_per_sec": round(steps_per_sec, 3),
                "achieved_flops_per_sec": round(
                    flops_per_step * steps_per_sec),
                "peak_bytes": max(
                    (c.get("peak_bytes") or 0) for c in insights),
            }

    # peak bf16 FLOPs from the actual chip (device_kind), not an env default
    kind = jax.devices()[0].device_kind.lower()
    if "v5p" in kind or "v5 p" in kind:
        peak = 459e12
    elif "v5" in kind and ("lite" in kind or "v5e" in kind):
        peak = 197e12
    elif "v4" in kind:
        peak = 275e12
    elif "v6" in kind:  # trillium
        peak = 918e12
    else:
        peak = 197e12
    if xla_cost is not None:
        xla_cost["xla_mfu"] = round(
            xla_cost["achieved_flops_per_sec"] / peak, 4)

    # device-memory accounting for this config: the measured per-step
    # watermark (executor samples every run; the window covers compile +
    # warmup + timed loops) reconciled against the static
    # program_peak_bytes estimate of the compiled train step. The
    # reconciliation carries its own agreement bound, so BENCH rounds
    # record not just the peak but whether the estimate can be trusted.
    _memwatch.sample()
    estimates = [c.get("peak_bytes") for c in insights]
    measured = float(_memwatch.window_peak())
    static_peak = max((e for e in estimates if e), default=0)
    memory = {
        # the gated metric: measured watermark when sampling works on
        # this backend, else the static estimate; None (-> perf_gate
        # SKIP) when BOTH are unavailable — a 0 would read as a perfect
        # lower-is-better score and poison the rolling median
        "peak_hbm_bytes": (int(measured) if measured > 0
                           else int(static_peak) if static_peak else None),
        "measured_peak_bytes": int(measured) if measured > 0 else None,
        "static_peak_bytes": int(static_peak) if static_peak else None,
        # the donation-adjusted static peak (args+outs+temps minus the
        # bytes aliased in place over donated params): what the step
        # actually holds live — the spread vs static_peak_bytes is the
        # donated state, and the donation tests gate that it stays >0
        "donated_peak_bytes": (max(
            (c.get("donated_peak_bytes") or 0 for c in insights),
            default=0) or None),
        "source": (_memwatch.totals().get("source")
                   if measured > 0 else "estimate"),
        "reconciliation": _memwatch.reconcile(
            estimates=estimates,
            measured_peak=measured if measured > 0 else None),
    }
    # median steady-state step latency, from the same window the
    # throughput headline uses (no re-derivation from batch*seq later)
    step_seconds = med_dt / iters

    # loss trajectory for tools/curve_gate.py: a short UNTIMED tail of
    # steps fetching the loss each iteration (the timed windows above
    # fetch only at their boundaries, so the headline stays free of
    # per-step host syncs). Training continues from the timed state on
    # the same seeded batch, so the curve is deterministic enough for
    # the band comparison; rounds embed it in BENCH_r*.json and the
    # curve gate judges fresh rounds against that history.
    traj_iters = 24
    base_step = 3 + 3 * iters  # warmup + timed windows already run
    traj_steps, traj_loss = [], []
    for i in range(traj_iters):
        loss = exe.run(main_prog, feed=feed, fetch_list=[io["loss"]],
                       scope=scope)[0]
        traj_steps.append(base_step + i)
        traj_loss.append(round(float(np.asarray(loss)), 6))
    trajectory = {"steps": traj_steps, "loss": traj_loss}

    # comms plane: what the compiled plan says each step ships
    # (shard_insight's HLO summary on the train-step program — 0 on one
    # chip, and the reconciliation below is the gate that keeps the
    # single-chip step free of surprise collectives) vs what the
    # collective byte counters measured over this config's steps
    total_steps = base_step + traj_iters
    predicted_per_step = max(
        ((c.get("collectives") or {}).get("payload_bytes_total", 0)
         for c in insights), default=0)
    coll_after = _shard.measured_collective_bytes()
    measured_logical = (coll_after["logical_bytes"]
                        - coll_before["logical_bytes"])
    comms_plane = {
        "predicted_collective_bytes": int(predicted_per_step),
        "predicted_total_bytes": int(predicted_per_step * total_steps),
        "measured_wire_bytes": int(coll_after["wire_bytes"]
                                   - coll_before["wire_bytes"]),
        "measured_logical_bytes": int(measured_logical),
        "steps": total_steps,
        "reconciliation": _shard.reconcile(
            predicted_per_step * total_steps,
            measured_bytes=measured_logical),
    }

    return (achieved / peak, tok_s, n_params, window_tok_s, xla_cost,
            goodput_breakdown, memory, step_seconds, trajectory,
            comms_plane)


def main():
    import paddle_tpu as paddle

    paddle.enable_static()
    from paddle_tpu.ops import attention

    baseline_mfu = 0.40  # A100+NCCL-class MFU on this workload (north star)

    # opt-in tracing rider: with PADDLE_TPU_TRACE_DIR set, each
    # benchmarked config runs under the tracer and drops its own chrome
    # trace next to the metrics snapshot (table printing suppressed —
    # stdout must stay the single JSON result line)
    from paddle_tpu import flags as _flags

    trace_dir = _flags.env_flag("PADDLE_TPU_TRACE_DIR") or None

    def traced(tag, **kw):
        if not trace_dir:
            return bench_config(**kw)
        from paddle_tpu import profiler

        profiler.start_profiler()
        try:
            return bench_config(**kw)
        finally:
            profiler.stop_profiler(
                profile_path=os.path.join(trace_dir, f"bench_trace.{tag}.json"),
                print_table=False)
            # the env-registered atexit flush must not re-export these
            # events as a stale trace.rank0.json next to the per-run files
            profiler.clear_events()

    (mfu, tok_s, n_params, windows, xla_cost, gp, mem, step_s,
     traj, comms) = traced("gpt2s_seq512", batch=8, seq=512, iters=80)

    flash_before = attention.FLASH_DISPATCH_COUNT
    (mfu_long, tok_s_long, _, windows_long, xla_cost_long, gp_long,
     mem_long, _step_s_long, traj_long, comms_long) = traced(
        "gpt2s_seq2048", batch=8, seq=2048, iters=40)
    flash_hit = attention.FLASH_DISPATCH_COUNT > flash_before
    assert flash_hit, "long-seq config silently fell back to the XLA path"

    # opt-in observability rider: PADDLE_TPU_METRICS_PATH=<file> writes
    # the JSON metrics snapshot (executor compile/run series, per-op
    # context) next to the bench result, so BENCH_r*.json rounds carry
    # the telemetry that explains their numbers (tools/obs_report.py
    # renders it)
    metrics_path = _flags.env_flag("PADDLE_TPU_METRICS_PATH") or None
    if metrics_path:
        from paddle_tpu import monitor

        monitor.stat_set("bench_tokens_per_sec", tok_s)
        monitor.stat_set("bench_long_seq_tokens_per_sec", tok_s_long)
        monitor.write_snapshot(metrics_path)

    result = {
        "metric": "gpt2s_pretrain_mfu",
        "value": round(mfu, 4),
        "unit": "MFU (model-flops util, bf16, 1 chip)",
        "vs_baseline": round(mfu / baseline_mfu, 3),
        "tokens_per_sec": round(tok_s),
        # median steady-state step latency (seconds/step): the second
        # lower-is-better metric the perf gate tracks
        "step_seconds": round(step_s, 6),
        "window_tokens_per_sec": [round(w) for w in windows],
        "params": n_params,
        "goodput": gp,
        # top-level copy of the goodput comms headline so perf_gate's
        # collective_fraction check reads it like mfu/peak_hbm_bytes
        "collective_fraction": gp.get("collective_fraction"),
        # per-config peak HBM (measured watermark, or the static
        # estimate when the backend reports no allocator stats) — the
        # lower-is-better metric tools/perf_gate.py gates alongside MFU
        "peak_hbm_bytes": mem["peak_hbm_bytes"],
        "memory": mem,
        # the convergence counterpart of the perf metrics: a downsampled
        # loss trajectory + final loss per config, so BENCH_r*.json
        # history carries the reference curves tools/curve_gate.py
        # gates fresh rounds (and real training journals) against
        "loss_trajectory": traj,
        "final_loss": traj["loss"][-1],
        # comms plane: HLO-predicted collective bytes per step vs the
        # measured byte counters, with the reconciliation verdict — the
        # predicted-vs-measured pair MULTICHIP rounds record per mode
        "comms_plane": comms,
        "predicted_collective_bytes": comms["predicted_collective_bytes"],
        "long_seq": {
            "seq": 2048,
            "value": round(mfu_long, 4),
            "vs_baseline": round(mfu_long / baseline_mfu, 3),
            "tokens_per_sec": round(tok_s_long),
            "window_tokens_per_sec": [round(w) for w in windows_long],
            "flash_path_hit": flash_hit,
            "goodput": gp_long,
            "peak_hbm_bytes": mem_long["peak_hbm_bytes"],
            "memory": mem_long,
            "loss_trajectory": traj_long,
            "final_loss": traj_long["loss"][-1],
            "comms_plane": comms_long,
            "predicted_collective_bytes":
                comms_long["predicted_collective_bytes"],
        },
    }
    # XLA cost-analysis utilization (when the insight capture ran): the
    # compiled program's own FLOPs next to the analytic-model headline,
    # so BENCH_*.json rounds carry utilization, not just latency
    if xla_cost is not None:
        result["flops_per_step"] = xla_cost["flops_per_step"]
        result["achieved_flops_per_sec"] = xla_cost["achieved_flops_per_sec"]
        result["steps_per_sec"] = xla_cost["steps_per_sec"]
        result["xla_cost"] = xla_cost
    if xla_cost_long is not None:
        result["long_seq"]["flops_per_step"] = xla_cost_long["flops_per_step"]
        result["long_seq"]["achieved_flops_per_sec"] = (
            xla_cost_long["achieved_flops_per_sec"])
        result["long_seq"]["xla_cost"] = xla_cost_long
    print(json.dumps(result))


if __name__ == "__main__":
    main()
